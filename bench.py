"""Headline benchmark: dense JLT sketch-apply throughput (GB/s/chip).

BASELINE.json config 1 scaled to saturate one chip: rowwise JLT apply
A·Sᵀ on a dense 8192×8192 matrix with sketch size 1024 (ref:
sketch/JLT.hpp + sketch/dense_transform_Elemental_local.hpp). The sketch
operator is generated on the fly from (seed, counter); on TPU the apply
runs through the fused Pallas generation+matmul kernel
(sketch/pallas_dense.py) at the SHIPPING DEFAULT precision regime,
"bf16x3" (error-compensated 3-pass split, on-chip oracle-certified at
1e-4 — benchmarks/tpu_validation_r03.txt); the conservative "f32"
(Precision.HIGHEST) and throughput-only single-pass "bf16" regimes are
measured alongside and reported as extra fields.

Wedge-proofing (the round-1 failure mode was an indefinite hang inside
TPU backend init on a wedged tunnel): every backend touch happens in a
*subprocess* with a bounded timeout — first a cheap probe, retried with
backoff, then the measurement itself — under one global deadline. On
exhaustion the script still prints the JSON line, with an explicit
``error`` field, instead of hanging the round. A FIRST probe that exits
with a hard error (backend init raised — dead tunnel, absent hardware)
fails fast: no retries, straight to the committed-capture fallback
(r4/r5 burned ~450s of escalating probe timeouts learning nothing).
``SKYLARK_BENCH_MAX_WALL`` caps the whole orchestration below the
retry deadline.

Other modes: ``--solver`` (engine compile-vs-execute split),
``--serve`` (microbatch serving throughput A/B, batched vs sequential
dispatch, plus the r12 kernel-selection A/B: autotuned per-bucket
pallas-vs-XLA flush selection against forced XLA, with per-bucket
outcomes), ``--fleet`` (N-replica router vs single-executor A/B with a
one-replica drain-failover leg), ``--boot`` (fleet-boot cold-start
A/B: fresh-process time-to-first-result with vs without a warmup pack,
zero-backend-compile proof — docs/performance), ``--stamp`` (oracle
certification line).

Each timed iteration consumes the FULL sketch output (the loop carries
sum(abs(SA)) back into the next input), so XLA cannot dead-code-eliminate
any part of the contraction; per-iteration time is the slope between a
2-iteration and a 12-iteration loop, cancelling dispatch/tunnel latency.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

METRIC = "jlt_sketch_apply_GBps_per_chip"
DEADLINE = float(os.environ.get("SKYLARK_BENCH_DEADLINE", "480"))
PROBE_TIMEOUT = float(os.environ.get("SKYLARK_BENCH_PROBE_TIMEOUT", "75"))
CHILD_TIMEOUT = float(os.environ.get("SKYLARK_BENCH_CHILD_TIMEOUT", "360"))


# ---------------------------------------------------------------------------
# child: the actual measurement (runs in a subprocess)
# ---------------------------------------------------------------------------


def run(m: int = 8192, n: int = 8192, s: int = 1024, repeats: int = 5,
        precision: str = "bf16x3"):
    """Measure one regime. ``precision`` ∈ {f32, bf16x3, bf16} selects the
    fused-kernel contraction regime; ``xla_high``/``xla_highest`` measure
    the PLAIN XLA path (materialize S, one gemm) at that matmul
    precision. Note the semantics of the XLA numbers: S generation is
    loop-invariant inside the timed iteration, so XLA hoists it and the
    slope measures the STEADY-STATE REUSE regime — generation fully
    amortized, the upper bound that materialize-once-and-reuse buys
    (e.g. a feature map applied every solver iteration). The kernel
    numbers pay generation on every apply (its regime is one-shot). The
    A/B therefore brackets the dispatch decision rather than settling it
    for one-shot applies."""
    import contextlib

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import JLT, ROWWISE
    from libskylark_tpu.sketch import params as sketch_params
    from libskylark_tpu.sketch import pallas_dense as pd

    xla_mode = precision.startswith("xla")
    prev_use_pallas = sketch_params.get_use_pallas()
    prev_precision = sketch_params.get_pallas_precision()
    try:
        # globals are mutated INSIDE the try: a setup failure (e.g.
        # device_put on a wedged TPU) must not leak use_pallas=False into
        # the rest of the process (run_all runs several benches in one
        # interpreter)
        if xla_mode:
            sketch_params.set_use_pallas(False)
            prec_ctx = jax.default_matmul_precision(
                {"xla_high": "high", "xla_highest": "highest"}[precision])
        else:
            sketch_params.set_use_pallas(True)
            sketch_params.set_pallas_precision(precision)
            prec_ctx = contextlib.nullcontext()
        ctx = Context(seed=0)
        jlt = JLT(n, s, ctx)
        key = jlt._alloc.key
        use_pallas = pd.available() and not xla_mode

        rng = np.random.default_rng(1)
        A = jax.device_put(jnp.asarray(
            rng.standard_normal((m, n), dtype=np.float32)))

        if use_pallas:
            # runtime verification, not just planning: a Mosaic compile
            # failure makes rowwise_apply return None (XLA fallback), and
            # a record labeled with the planned kernel config while
            # timing the fallback would be a lie
            use_pallas = pd.rowwise_apply(
                key, jlt.dist, A, s, jlt.scale, precision=precision
            ) is not None

        def one_apply(X):
            if use_pallas:
                out = pd.rowwise_apply(key, jlt.dist, X, s, jlt.scale,
                                       precision=precision)
                if out is not None:
                    return out
            return jlt.apply(X, ROWWISE)

        def iterate(X, K):
            def body(_, acc):
                SA = one_apply(X + acc)
                # consume every element of SA; scale keeps the carry ~0
                # so the input matrix is numerically unchanged between
                # iterations
                return jnp.sum(jnp.abs(SA)).astype(jnp.float32) * 1e-37
            return lax.fori_loop(0, K, body, jnp.float32(0.0))

        k1, k2 = 2, 12
        f1 = jax.jit(lambda X: iterate(X, k1))
        f2 = jax.jit(lambda X: iterate(X, k2))
        # the precision context must cover the timed calls too, not just
        # the warm-up: jax_default_matmul_precision is part of the trace
        # context, so a call outside it would silently retrace (and time)
        # at the process-wide default
        with prec_ctx:
            float(f1(A))  # compile + warm
            float(f2(A))

            best = float("inf")
            best_f2 = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                float(f1(A))
                t1 = time.perf_counter()
                float(f2(A))
                t2 = time.perf_counter()
                best = min(best, ((t2 - t1) - (t1 - t0)) / (k2 - k1))
                best_f2 = min(best_f2, t2 - t1)
            if best <= 0:
                # slope lost in timer noise (sub-ms applies): fall back
                # to the dispatch-inclusive per-apply bound instead of a
                # negative rate
                best = best_f2 / k2

            trace_dir = os.environ.get("SKYLARK_BENCH_TRACE")
            if trace_dir:  # one traced apply for offline kernel analysis
                with jax.profiler.trace(trace_dir):
                    float(f2(A))

        # the plan the kernel ACTUALLY ran (tuning knobs can be silently
        # adjusted: _qualify shrinks over-budget m-tiles, _select_pipe
        # drops an unfittable pipeline buffer) — recorded so sweep rows
        # label measurements with the effective config, not the request
        plan = (dict(pd.effective_plan(jlt.dist, (m, n), A.dtype, s,
                                       seq_axis=1, precision=precision),
                     runtime_verified=True)
                if use_pallas else {"kernel": False, "plan_id": "xla"})
    finally:
        sketch_params.set_use_pallas(prev_use_pallas)
        sketch_params.set_pallas_precision(prev_precision)

    bytes_moved = 4 * (m * n + m * s)
    gbps = bytes_moved / best / 1e9
    _record_plan_measurement(plan, m, n, s, gbps)
    return gbps, best, plan


def _record_plan_measurement(plan: dict, m: int, n: int, s: int,
                             gbps: float) -> None:
    """Feed a real kernel measurement back into the autotuner plan cache
    (libskylark_tpu/tune/) so the next dispatch — and the next round —
    serves the certified winner. Only runtime-verified kernel plans
    qualify (the XLA fallback is recorded by its absence); best-value-
    wins semantics live in the cache. Never a failure mode.
    SKYLARK_BENCH_RECORD_PLANS=0 opts out (e.g. a sweep that must not
    write winners mid-exploration)."""
    if not plan.get("kernel"):
        return
    if os.environ.get("SKYLARK_BENCH_RECORD_PLANS", "1") == "0":
        return
    try:
        from libskylark_tpu import tune

        if plan.get("precision") not in tune.plans.ORACLE_PRECISIONS:
            # the throughput-only regimes (bf16/bf16gen2) are measured
            # as informational extras; a cached winner is served by the
            # DEFAULT dispatch, which must never auto-select a regime
            # outside the 1e-4 oracle
            return

        w = tune.dense_workload("normal", (m, n), "float32", s,
                                seq_axis=1)
        p = tune.Plan("pallas", m_tile=plan["m_tile"],
                      precision=plan.get("precision"),
                      pipeline=bool(plan.get("pipelined")))
        tune.record_measurement(w, p, gbps, unit="GB/s",
                                extra={"metric": METRIC})
    except Exception:
        pass


# bf16 MXU peak of the bench chip, for the MFU field. v5e ≈ 197 TFLOP/s;
# override for other parts via env (the record labels the assumption).
# Parsed defensively: a malformed or non-positive override must not crash
# the parent before it can print its one JSON line (the wedge-proofing
# contract), nor produce Infinity in the record.
def _peak_bf16_tflops() -> float:
    try:
        v = float(os.environ.get("SKYLARK_PEAK_BF16_TFLOPS", "197"))
    except ValueError:
        return 197.0
    return v if v > 0 else 197.0


_PEAK_BF16_TFLOPS = _peak_bf16_tflops()


# The kernel-relevant closure a certification stamp must cover: the
# kernel itself, the tuning knobs that select its regimes/tiles, and the
# generation streams whose bits the oracle compares. A stamp hashing
# only pallas_dense.py lets a post-certification change to params.py or
# randgen.py ride a stale certification (ADVICE r5).
_KERNEL_CLOSURE = (
    os.path.join("libskylark_tpu", "sketch", "pallas_dense.py"),
    os.path.join("libskylark_tpu", "sketch", "params.py"),
    os.path.join("libskylark_tpu", "base", "randgen.py"),
)


def _closure_sha256(here: str):
    """sha256 over the per-file sha256s of the kernel closure, in
    _KERNEL_CLOSURE order; None when any file is unreadable."""
    import hashlib

    h = hashlib.sha256()
    for rel in _KERNEL_CLOSURE:
        try:
            with open(os.path.join(here, rel), "rb") as fh:
                h.update(hashlib.sha256(fh.read()).digest())
        except OSError:
            return None
    return h.hexdigest()


def _stamp_line() -> str:
    """The certification line the tunnel-watcher steps scripts append to
    benchmarks/.tpu_oracle_recert_r*: kernel hash (back-compat field) +
    the closure hash freshness actually checks against. Printed by
    ``python bench.py --stamp`` so the scripts can't drift from the
    verifier."""
    import hashlib

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, _KERNEL_CLOSURE[0]), "rb") as fh:
            kern = hashlib.sha256(fh.read()).hexdigest()
    except OSError:
        kern = "unreadable"
    return (f"kernel_sha256={kern} "
            f"closure_sha256={_closure_sha256(here) or 'unreadable'}")


def _stamp_fresh_against(stamp_text: str, here: str) -> bool:
    """Whether a stamp's content certifies the CURRENT working tree:
    its closure_sha256 must match the current kernel closure. Legacy
    stamps carrying only kernel_sha256 are treated as STALE — they
    certify one file of a three-file closure, exactly the ride-along
    the closure hash exists to stop."""
    cur = _closure_sha256(here)
    return cur is not None and f"closure_sha256={cur}" in stamp_text


def _fresh_stamp() -> bool:
    """True when ANY round's on-chip oracle stamp content-matches the
    current kernel CLOSURE (pallas_dense.py + sketch/params.py +
    base/randgen.py; bench.py compares hashes, not mtimes). Used to skip
    the ~75s probe: a fresh stamp means a live window already ran the
    full on-chip oracle battery against this exact kernel recently —
    go straight to the measurement and spend the window budget there."""
    here = os.path.dirname(os.path.abspath(__file__))
    cur = _closure_sha256(here)  # hashed once, checked per stamp
    if cur is None:
        return False
    for pth in glob.glob(os.path.join(
            here, "benchmarks", ".tpu_oracle_recert_r*")):
        try:
            with open(pth) as fh:
                if f"closure_sha256={cur}" in fh.read():
                    return True
        except OSError:
            continue
    return False


def _child() -> None:
    import jax

    # persistent compilation cache: the headline apply's 20-40s XLA
    # compile dominates this script's cold start (r4 verdict #6 —
    # cold_start_wall_s is the reason three rounds of BENCH_r*.json are
    # null); with the cache a re-run inside the same working tree (the
    # watcher's capture, then the driver's) compiles once per kernel
    # change instead of once per process
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization, never a failure mode

    platform = jax.default_backend()
    m, n, s = 8192, 8192, 1024
    # shipping default bf16x3; SKYLARK_BENCH_PRECISION lets the watcher
    # sweep alternative regimes (e.g. the 2-pass "bf16gen2") without a
    # code change mid-window
    precision = os.environ.get("SKYLARK_BENCH_PRECISION", "bf16x3")
    gbps, secs, plan = run(m, n, s, precision=precision)
    tflops = 2.0 * m * n * s / secs / 1e12
    rec = {
        "platform": platform,
        "value": round(gbps, 3),
        "secs_per_apply": secs,
        "precision": precision,
        "plan": plan,
        # the serving plan's identity, top-level: sweep tooling and the
        # round verdicts grep for WHICH plan produced the number
        "plan_id": plan.get("plan_id"),
        "tflops": round(tflops, 2),
        # fraction of single-pass bf16 MXU peak; the bf16x3 regime issues
        # 3 passes per logical FLOP, so its ceiling is ~1/3
        "mfu_vs_bf16_peak": round(tflops / _PEAK_BF16_TFLOPS, 4),
        "peak_bf16_tflops_assumed": _PEAK_BF16_TFLOPS,
    }
    # Print the headline immediately — the informational extras below
    # must not be able to void an already-successful measurement if the
    # child is killed at CHILD_TIMEOUT mid-extra.
    print("CHILD_RESULT " + json.dumps(rec), flush=True)
    # informational extras: the conservative and throughput-only kernel
    # regimes, plus the plain-XLA one-shot-materialization path at the
    # matched (bf16x3-grade) precision — the regeneration-vs-
    # materialization A/B. SKYLARK_BENCH_SKIP_EXTRAS=1 skips them so a
    # tuning sweep (one point per process) spends a live tunnel window on
    # sweep points instead of re-measuring the same three extras
    if os.environ.get("SKYLARK_BENCH_SKIP_EXTRAS") == "1":
        return
    # bf16gen2 first: it is the 2-pass candidate for the >=100 GB/s
    # target (VERDICT r4 #3) — if the child is killed mid-extras, the
    # highest-value A/B number must be the one already captured
    for regime in ("bf16gen2", "f32", "bf16", "xla_high"):
        if regime == precision:
            continue  # already the headline
        try:
            gbps_x, _, _ = run(precision=regime, repeats=3)
            print("CHILD_EXTRA " + json.dumps(
                {f"{regime}_GBps": round(gbps_x, 3)}), flush=True)
        except Exception:
            pass


def _probe() -> None:
    import jax

    devs = jax.devices()
    print(f"PROBE_OK {jax.default_backend()} {len(devs)}", flush=True)


# ---------------------------------------------------------------------------
# probe health: structured hardware truth in every record
# ---------------------------------------------------------------------------
# The tunnel has been dead since r02 and the old records carried only
# bare "probe failed rc=-1 TIMEOUT" strings buried in `error`. Every
# BENCH/MULTICHIP record now embeds a structured block — status,
# reason, measured probe latency, and the newest committed on-chip
# success — so the trajectory shows exactly when the tunnel returns
# (and how long a live probe takes when it does).

_PROBE_HEALTH = {"status": "not_probed", "platform": None,
                 "reason": None, "latency_s": None, "attempts": 0}


def _record_probe(status: str, platform, reason, latency_s) -> None:
    _PROBE_HEALTH.update(
        status=status, platform=platform,
        reason=(None if reason is None
                else str(reason).replace("\n", " ")[-300:]),
        latency_s=(None if latency_s is None else round(latency_s, 3)),
        attempts=_PROBE_HEALTH["attempts"] + 1)


def _last_probe_success():
    """The newest committed on-chip headline record — the
    ``last-success stamp`` of the probe-health block (when the tunnel
    last demonstrably worked, and what it measured)."""
    here = os.path.dirname(os.path.abspath(__file__))
    cands = []
    for pth in glob.glob(os.path.join(
            here, "benchmarks", "results_tpu_r*_headline.json")):
        mm = re.search(r"results_tpu_r(\d+)_headline\.json$", pth)
        if mm:
            cands.append((int(mm.group(1)), pth))
    if not cands:
        return None
    rnd, path = max(cands)
    out = {"round": rnd, "file": os.path.basename(path)}
    try:
        with open(path) as fh:
            rec = json.load(fh)
        out["value"] = rec.get("value")
        for k in ("timestamp", "captured_at", "date"):
            if rec.get(k) is not None:
                out["stamp"] = rec[k]
                break
        else:
            out["stamp"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path)))
    except Exception as e:
        out["error"] = repr(e)
    return out


def probe_health_block(run_probe: bool = False,
                       timeout: float = 20.0) -> dict:
    """The structured probe-health block. ``run_probe=True`` runs a
    bounded ``--probe`` subprocess first when this process has not
    probed yet (the MULTICHIP path — ``__graft_entry__`` attaches the
    block to its record)."""
    if run_probe and _PROBE_HEALTH["attempts"] == 0:
        t0 = time.monotonic()
        rc, out = _sub("--probe", timeout)
        dt = time.monotonic() - t0
        if rc == 0 and "PROBE_OK" in out:
            plat = out.split("PROBE_OK", 1)[1].split()[0]
            _record_probe("live", plat, None, dt)
        else:
            _record_probe("dead", None,
                          f"rc={rc}: {out[-200:]}", dt)
    block = dict(_PROBE_HEALTH)
    block["last_success"] = _last_probe_success()
    return block


# ---------------------------------------------------------------------------
# solver-level measurement: fused pipelines + executable cache
# ---------------------------------------------------------------------------


def _solver(m: int = 1024, n: int = 512, rank: int = 8) -> None:
    """Per-solver compile-vs-execute split for the engine-compiled
    pipelines (``python bench.py --solver``; backend-agnostic — run with
    JAX_PLATFORMS=cpu for a hardware-free record).

    Reports, per the r7 acceptance criteria: the fused
    ``approximate_svd`` dispatching as ONE executable call per solve
    (vs the per-op eager profile path, whose backend-compile count is
    measured alongside), the KRR loops making zero host syncs per
    iteration (proved structurally: the BCD program traces end-to-end
    into a single ``lax.while_loop`` — any host sync would be a
    ConcretizationError), and the executable-cache hit rate for the
    run. Prints exactly one JSON line."""
    import jax
    import jax.monitoring as monitoring
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import Context, engine, ml, nla
    from libskylark_tpu.ml import krr as krr_mod
    from libskylark_tpu.utility import timer as phase_timer

    compiles = {"n": 0}

    def _on_event(name, dur, **kw):
        if name == "/jax/core/compile/backend_compile_duration":
            compiles["n"] += 1

    monitoring.register_event_duration_secs_listener(_on_event)

    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    p = nla.ApproximateSVDParams(num_iterations=2)
    engine.reset()

    # -- randomized SVD: per-op eager (the profiling path) vs fused --
    phase_timer.set_enabled(True)   # selects the unfused variant
    c0, t0 = compiles["n"], time.perf_counter()
    jax.block_until_ready(nla.approximate_svd(A, rank, Context(seed=1), p))
    eager_cold = time.perf_counter() - t0
    eager_compiles = compiles["n"] - c0
    t0 = time.perf_counter()
    jax.block_until_ready(nla.approximate_svd(A, rank, Context(seed=1), p))
    eager_warm = time.perf_counter() - t0
    phase_timer.set_enabled(False)

    c0, t0 = compiles["n"], time.perf_counter()
    jax.block_until_ready(nla.approximate_svd(A, rank, Context(seed=1), p))
    fused_cold = time.perf_counter() - t0
    fused_compiles = compiles["n"] - c0
    calls0 = engine.stats().executions
    t0 = time.perf_counter()
    jax.block_until_ready(nla.approximate_svd(A, rank, Context(seed=1), p))
    fused_warm = time.perf_counter() - t0
    fused_calls_per_solve = engine.stats().executions - calls0

    # -- KRR: device-resident loops --
    d = 16
    X = jnp.asarray(rng.standard_normal((512, d)).astype(np.float32))
    Y = jnp.asarray(rng.standard_normal((512, 1)).astype(np.float32))
    k = ml.Gaussian(d, sigma=2.0)
    kp = ml.KrrParams(iter_lim=20, tolerance=1e-6)
    t0 = time.perf_counter()
    transforms, W = ml.large_scale_kernel_ridge(
        k, X, Y, 0.1, 64, Context(seed=3), kp)
    jax.block_until_ready(W)
    krr_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, W2 = ml.large_scale_kernel_ridge(
        k, X, Y, 0.1, 64, Context(seed=3), kp)
    jax.block_until_ready(W2)
    krr_warm = time.perf_counter() - t0
    # zero-host-sync proof: the whole BCD solve traces into one program
    # whose sweep loop is a single lax.while_loop — a host sync anywhere
    # inside would make this trace raise
    run = krr_mod._bcd_program(transforms, 20, 1e-6)
    jaxpr = jax.make_jaxpr(run)(X, Y, jnp.float32(0.1))
    bcd_while = sum(1 for e in jaxpr.jaxpr.eqns
                    if e.primitive.name == "while")

    st = engine.stats()
    rec = {
        "metric": "solver_pipeline_engine",
        "platform": jax.default_backend(),
        "svd": {
            "shape": [m, n], "rank": rank,
            "executable_calls_per_solve": fused_calls_per_solve,
            "backend_compiles_fused": fused_compiles,
            "backend_compiles_eager": eager_compiles,
            "fused_cold_s": round(fused_cold, 4),
            "fused_warm_s": round(fused_warm, 4),
            "eager_cold_s": round(eager_cold, 4),
            "eager_warm_s": round(eager_warm, 4),
        },
        "krr_bcd": {
            "host_syncs_per_iteration": 0,
            "proof": "traced end-to-end; sweep loop is lax.while_loop",
            "while_loops_in_program": bcd_while,
            "cold_s": round(krr_cold, 4),
            "warm_s": round(krr_warm, 4),
        },
        "engine": dict(st.to_dict(), cache_entries=len(engine.cache())),
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# qos-level measurement: adaptive-vs-static batching A/B (docs/qos)
# ---------------------------------------------------------------------------


def _qos(rounds: int = 6, per_round: int = 16) -> None:
    """Adaptive-vs-static A/B for the QoS subsystem (``python bench.py
    --qos``; backend-agnostic — run with JAX_PLATFORMS=cpu for the
    hardware-free record).

    Workload: an interactive request *trickle* (one in flight at a
    time — the pattern a static linger taxes hardest: every request
    waits out the full linger alone) over a deliberately generous
    static config (linger 20 ms), with a best_effort burst riding
    along each round. The *static* side serves it as configured; the
    *adaptive* side runs the controller (tight interactive SLO), which
    walks the bucket's linger target down until the trickle stops
    paying for batching it never gets. The record carries both sides'
    final-round interactive p99 (client-observed), the controller's
    adjustment counters, the zero-compile proof across both measured
    windows, and a bit-equality check between the sides (same
    transform, same bits regardless of scheduling policy). The CI qos
    gate asserts adaptive p99 <= static p99 — adaptation must not
    regress the interactive class against the static baseline."""
    import jax
    import numpy as np

    from libskylark_tpu import Context, engine, qos
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.qos.controller import AdaptiveController

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    T = sk.CWT(256, 32, ctx)
    ops = [rng.standard_normal((256, 3 + i % 3)).astype(np.float32)
           for i in range(per_round)]
    be_ops = ops[: per_round // 2]

    reg = qos.TenantRegistry()
    reg.register("ui", qos.INTERACTIVE)
    reg.register("etl", qos.BEST_EFFORT)

    slo_env = "SKYLARK_QOS_SLO_INTERACTIVE_MS"

    def run_mode(adaptive: bool):
        ex = engine.MicrobatchExecutor(
            max_batch=8, linger_us=20_000, max_queue=1024,
            workers=2, tenants=reg)
        ctrl = (AdaptiveController(ex, start=False)
                if adaptive else None)
        # capacity-ladder warmup (shared executable cache: the second
        # mode's warmup is all hits)
        cap = 1
        while cap <= 8:
            futs = [ex.submit_sketch(T, ops[i % per_round],
                                     tenant="ui")
                    for i in range(cap)]
            ex.flush()
            [f.result(timeout=120) for f in futs]
            cap *= 2
        st0 = engine.stats()
        warm = (st0.misses, st0.recompiles)
        last_round_lat: list = []
        sample = None
        for r in range(rounds):
            # best_effort burst rides along (not awaited serially)
            be = [ex.submit_sketch(T, A, tenant="etl")
                  for A in be_ops]
            lats = []
            for i in range(per_round):
                t0 = time.perf_counter()
                out = ex.submit_sketch(
                    T, ops[i], tenant="ui").result(timeout=120)
                lats.append(time.perf_counter() - t0)
                if sample is None:
                    sample = np.asarray(out)
            for f in be:
                f.result(timeout=120)
            if ctrl is not None:
                ctrl.tick()
            last_round_lat = lats
        st1 = engine.stats()
        stats = ex.stats()["qos"]
        targets = dict(stats["targets"])
        ctrl_stats = ctrl.stats() if ctrl is not None else None
        ex.shutdown()
        last_round_lat.sort()
        p99 = last_round_lat[
            min(int(0.99 * (len(last_round_lat) - 1) + 0.5),
                len(last_round_lat) - 1)]
        return {
            "p99_interactive_last_round_s": round(p99, 6),
            "mean_interactive_last_round_s": round(
                float(np.mean(last_round_lat)), 6),
            "misses_measured": st1.misses - warm[0],
            "recompiles_measured": st1.recompiles - warm[1],
            "targets": targets,
            "controller": ctrl_stats,
            "by_class": {c: {k: stats["by_class"][c][k]
                             for k in ("admitted", "shed")}
                         for c in qos.CLASSES},
        }, sample

    engine.reset()
    prev_slo = os.environ.get(slo_env)
    os.environ[slo_env] = "5.0"    # the adaptive side's target
    try:
        static_rec, static_sample = run_mode(adaptive=False)
        adaptive_rec, adaptive_sample = run_mode(adaptive=True)
    finally:
        if prev_slo is None:
            os.environ.pop(slo_env, None)
        else:
            os.environ[slo_env] = prev_slo

    p99_s = static_rec["p99_interactive_last_round_s"]
    p99_a = adaptive_rec["p99_interactive_last_round_s"]
    rec = {
        "bench": "QOS",
        "backend": jax.default_backend(),
        "rounds": rounds,
        "per_round": per_round,
        "static": static_rec,
        "adaptive": adaptive_rec,
        "p99_ratio_adaptive_vs_static": (round(p99_a / p99_s, 4)
                                         if p99_s else None),
        "interactive_p99_no_regression": p99_a <= p99_s * 1.1,
        "bit_equal_across_modes": bool(
            np.array_equal(static_sample, adaptive_sample)),
        "zero_compiles_measured": not (
            static_rec["misses_measured"]
            or static_rec["recompiles_measured"]
            or adaptive_rec["misses_measured"]
            or adaptive_rec["recompiles_measured"]),
        "host_cores": os.cpu_count(),
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    ok = (rec["interactive_p99_no_regression"]
          and rec["bit_equal_across_modes"]
          and rec["zero_compiles_measured"]
          and (adaptive_rec["controller"] or {}).get(
              "adjustments", 0) >= 1)
    if not ok:
        sys.exit(1)


# ---------------------------------------------------------------------------
# the measurement ledger: best-for-host-class ratchet input
# ---------------------------------------------------------------------------


def _ledger_append(metric: str, value) -> None:
    """Append one line to ``benchmarks/ledger.json`` (JSON lines): the
    cross-run measurement ledger the CI ratchet reads. Each entry
    carries the metric, its value, the ``host_class`` the number is
    comparable within (platform + core count — an rps from a 4-core
    runner must never ratchet an 8-core one), and the probe-health
    block for provenance. The ledger is telemetry, not a gate:
    appending never fails a bench run."""
    try:
        try:
            import jax

            plat = jax.default_backend()
        except Exception:  # noqa: BLE001 — provenance, not a gate
            plat = "unknown"
        rec = {
            "metric": str(metric),
            "value": value,
            "host_class": f"{plat}-{os.cpu_count()}c",
            "probe_health": probe_health_block(),
        }
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "ledger.json")
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except Exception:  # noqa: BLE001 — never fail the bench for it
        pass


# ---------------------------------------------------------------------------
# serve-level measurement: microbatch coalescing vs sequential dispatch
# ---------------------------------------------------------------------------


def _serve(n_requests: int = 64, max_batch: int = 16,
           rounds: int = 5) -> None:
    """Throughput A/B for the microbatch serving layer (``python
    bench.py --serve``; backend-agnostic — run with JAX_PLATFORMS=cpu
    for the hardware-free record).

    Workload: ``n_requests`` in-flight small ragged requests per round.
    *Sequential* dispatches each request as its own engine-compiled
    exact-shape executable (the r7 status quo: N requests = N
    dispatches); *batched* submits the same requests to a
    :class:`MicrobatchExecutor` that coalesces them into padded
    ``vmap``-batched flushes. Both sides are fully warmed before the
    measured rounds, so the comparison is steady-state dispatch — the
    record carries the engine's miss/recompile deltas across the
    measured window to prove it (zero compiles after per-bucket
    warmup). Prints exactly one JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import Context, engine, ml
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.algorithms import regression as reg
    from libskylark_tpu.base import randgen
    from libskylark_tpu.ml import krr as krr_mod
    from libskylark_tpu.sketch import dense as sk_dense

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    s_dim = 32

    # ragged shapes inside ONE pow2 bucket class: (48..60, 112..128)
    # all pad to (64, 128) — padding waste is part of the measurement
    reqs = []
    for i in range(n_requests):
        m = 48 + (i % 4) * 4
        n = 112 + (i % 3) * 8
        T = sk.JLT(n, s_dim, ctx)
        A = rng.standard_normal((m, n)).astype(np.float32)
        kd = np.asarray(jax.random.key_data(T.allocation.key),
                        dtype=np.uint32)
        reqs.append((T, A, kd, np.float32(T.scale)))

    engine.reset()

    # -- sequential baseline: one exact-shape executable per request --
    def seq_one(kd, scale, A):
        return sk_dense.serve_apply(kd, scale, A, dist=randgen.Normal(),
                                    s_dim=s_dim, rowwise=True)

    cf_seq = engine.compiled(seq_one, name="serve_bench.sequential",
                             key_fn=lambda *a: ("seq", s_dim))

    def run_sequential():
        outs = [cf_seq(kd, scale, A) for (_, A, kd, scale) in reqs]
        jax.block_until_ready(outs)
        return outs

    run_sequential()                       # warm every exact shape
    seq_best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        run_sequential()
        seq_best = min(seq_best, time.perf_counter() - t0)
    rps_seq = n_requests / seq_best

    # -- batched: the microbatch executor --
    ex = engine.MicrobatchExecutor(max_batch=max_batch, linger_us=5000,
                                   max_queue=4 * n_requests, workers=2)

    def warm_capacities(submit_one, n_caps=max_batch):
        """Compile every pow2 capacity class of a bucket up front, so
        the measured window is provably compile-free no matter how the
        linger deadline fragments a round's cohorts."""
        cap = 1
        while cap <= n_caps:
            futs = [submit_one(i) for i in range(cap)]
            ex.flush()
            jax.block_until_ready([f.result(timeout=120) for f in futs])
            cap *= 2

    def run_batched():
        futs = [ex.submit_sketch(T, A, dimension=sk.ROWWISE)
                for (T, A, _, _) in reqs]
        outs = [f.result(timeout=60) for f in futs]
        jax.block_until_ready(outs)
        return outs

    warm_capacities(
        lambda i: ex.submit_sketch(reqs[i][0], reqs[i][1],
                                   dimension=sk.ROWWISE))
    b_out = run_batched()
    # engine.stats() is the LIVE counter block — capture ints, not the
    # object, and read the deltas before the secondary endpoints add
    # their own warmup compiles
    st = engine.stats()
    warm = (st.misses, st.recompiles)
    bat_best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        run_batched()
        bat_best = min(bat_best, time.perf_counter() - t0)
    measured_misses = engine.stats().misses - warm[0]
    measured_recompiles = engine.stats().recompiles - warm[1]
    rps_bat = n_requests / bat_best

    # correctness spot-check: a batched flush is bit-equal to the serve
    # layer's own capacity-1 sequential dispatch (lane invariance), and
    # numerically tight against the exact-shape sequential executables
    # (XLA's batched contraction may legitimately reorder f32 sums)
    ex1 = engine.MicrobatchExecutor(max_batch=1, linger_us=100)
    seq1 = [ex1.submit_sketch(T, A, dimension=sk.ROWWISE)
            for (T, A, _, _) in reqs]
    lane_equal = all(
        np.array_equal(np.asarray(b), np.asarray(f.result(timeout=60)))
        for b, f in zip(b_out, seq1))
    ex1.shutdown()
    seq_out = run_sequential()
    close = all(
        np.allclose(np.asarray(b), np.asarray(s), rtol=1e-4, atol=1e-5)
        for b, s in zip(b_out, seq_out))

    # -- secondary endpoints: solve + krr predict ride the same path --
    def endpoint_ab(submit_fn, seq_cf, seq_args, n_sub, timeout=60.0):
        warm_capacities(submit_fn)
        futs = [submit_fn(i) for i in range(n_sub)]
        jax.block_until_ready([f.result(timeout=timeout) for f in futs])
        for i in range(n_sub):
            seq_cf(*seq_args(i))
        t0 = time.perf_counter()
        futs = [submit_fn(i) for i in range(n_sub)]
        jax.block_until_ready([f.result(timeout=timeout) for f in futs])
        t_bat = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.block_until_ready([seq_cf(*seq_args(i))
                               for i in range(n_sub)])
        t_seq = time.perf_counter() - t0
        return {"rps_batched": round(n_sub / t_bat, 1),
                "rps_sequential": round(n_sub / t_seq, 1),
                "speedup": round(t_seq / t_bat, 2)}

    n_sub = max_batch * 2
    solve_reqs = []
    for i in range(n_sub):
        n = 100 + (i % 4) * 5
        Ts = sk.JLT(n, 24, ctx)
        As = rng.standard_normal((n, 6)).astype(np.float32)
        Bs = rng.standard_normal((n, 1)).astype(np.float32)
        kds = np.asarray(jax.random.key_data(Ts.allocation.key),
                         dtype=np.uint32)
        solve_reqs.append((Ts, As, Bs, kds, np.float32(Ts.scale)))

    def solve_seq(kd, scale, A, B):
        return reg.sketched_solve_serve(kd, scale, A, B,
                                        sketch_type="JLT", s_dim=24,
                                        method="qr")

    cf_solve = engine.compiled(solve_seq, name="serve_bench.seq_solve",
                               key_fn=lambda *a: ("seq-solve",))
    solve_ab = endpoint_ab(
        lambda i: ex.submit_solve(solve_reqs[i][1], solve_reqs[i][2],
                                  transform=solve_reqs[i][0]),
        cf_solve,
        lambda i: (solve_reqs[i][3], solve_reqs[i][4],
                   solve_reqs[i][1], solve_reqs[i][2]),
        n_sub)

    X = jnp.asarray(rng.standard_normal((64, 8)).astype(np.float32))
    Y = jnp.asarray(rng.standard_normal((64, 1)).astype(np.float32))
    kern = ml.Gaussian(8, sigma=2.0)
    coef = ml.kernel_ridge(kern, X, Y, 0.1)
    krr_queries = [
        rng.standard_normal((5 + (i % 8), 8)).astype(np.float32)
        for i in range(n_sub)
    ]

    def krr_seq(Xq, Xtr, C):
        return krr_mod.krr_predict_kernel(kern, Xq, Xtr, C)

    cf_krr = engine.compiled(krr_seq, name="serve_bench.seq_krr",
                             key_fn=lambda *a: ("seq-krr",))
    krr_ab = endpoint_ab(
        lambda i: ex.submit_krr_predict(kern, krr_queries[i], X, coef),
        cf_krr, lambda i: (krr_queries[i], X, coef), n_sub)

    # -- degraded-mode A/B: 1-in-64 injected flush faults ----------------
    # Same workload under a deterministic fault plan: every 64th flush
    # attempt raises, the executor's bisection re-executes the halves,
    # and the record captures what that isolation overhead costs in
    # throughput (the BENCH trajectory's resilience-tax row). The faults
    # are attempt-counted, not request-pinned, so bisection absorbs every
    # one — client-visible failures stay 0 (recorded to prove it).
    from libskylark_tpu.resilience import faults as _faults

    # snapshot the CLEAN stats first: the headline record's latency
    # percentiles / padding-waste / counters must not absorb the
    # isolation-retry traffic the degraded A/B is about to inject
    st = ex.stats()
    # ~1-in-64 REQUESTS = every (n_requests/max_batch)th flush attempt
    # for the 64-request rounds. Floor 3: after a failure at hit h ≡ 0
    # (mod every), the bisection halves run at hits h+1 and h+2 — with
    # every ≥ 3 neither is a multiple, so every injected fault is
    # absorbed in one split with zero client-visible failures (every=2
    # would fail a half, every=1 would fail every leaf)
    deg_every = max(n_requests // max_batch, 3)
    plan = {"seed": 0, "faults": [
        {"site": "serve.flush", "error": "IOError_", "every": deg_every}]}
    deg_failures = 0
    with _faults.fault_plan(plan):
        deg_best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            futs = [ex.submit_sketch(T, A, dimension=sk.ROWWISE)
                    for (T, A, _, _) in reqs]
            outs = []
            for f in futs:
                try:
                    outs.append(f.result(timeout=60))
                except Exception:  # noqa: BLE001 — counted, not fatal
                    deg_failures += 1
            jax.block_until_ready(outs)
            deg_best = min(deg_best, time.perf_counter() - t0)
    st1 = ex.stats()
    rps_deg = n_requests / deg_best
    degraded_mode = {
        "fault_rate": f"1/{deg_every} flush attempts "
                      f"(~1/{deg_every * max_batch} requests)",
        "rps_batched_degraded": round(rps_deg, 1),
        "rps_batched_clean": round(rps_bat, 1),
        "overhead_ratio": round(rps_bat / rps_deg, 3) if rps_deg else None,
        "flush_failures": st1["flush_failures"] - st["flush_failures"],
        "isolation_retries": (st1["isolation_retries"]
                              - st["isolation_retries"]),
        "client_visible_failures": deg_failures,
        "state_after": ex.state,
    }

    ex.shutdown()

    # -- kernel-selection A/B: autotuned per-bucket selection vs forced
    # XLA (r12). A 2-bucket serve mix is tuned OFFLINE (record_ranked
    # into an in-memory cache — the committed benchmarks/plan_cache.json
    # is never touched by a bench run), then the same storm runs once
    # with selection enabled (arg > env > plan cache > default) and once
    # forced onto the vmapped-XLA flush. On a CPU host the cost model's
    # interpret-mode penalty makes the tuner certify XLA for EVERY serve
    # bucket — interpret-mode pallas is a correctness surface, not a
    # speed surface — so the honest CPU record shows ~1x with
    # per-bucket "xla" outcomes; the kernel side of the A/B only opens
    # up on real silicon, where numbers ride the committed-record
    # protocol (the bench tunnel is dead — ROADMAP).
    from libskylark_tpu import tune as _tune

    kab_nreq, kab_batch = 16, 8
    cwt_reqs = []
    for i in range(kab_nreq):
        Tk = sk.CWT(40, 16, ctx)
        Ak = rng.standard_normal((40, 3 + i % 4)).astype(np.float32)
        cwt_reqs.append((Tk, Ak))
    jlt_reqs = [(reqs[i][0], reqs[i][1]) for i in range(kab_nreq)]

    prev_cache = _tune.set_cache(_tune.PlanCache(path=None))
    try:
        # tune every pow2 capacity class, not just kab_batch: the
        # measured storm's linger-fragmented cohorts flush at any of
        # them, and an untuned capacity would silently run the xla
        # DEFAULT while the record claimed a tuner decision ran
        buckets = {}
        cap = 1
        while cap <= kab_batch:
            buckets[f"cwt_cw_64x8_s16/b{cap}"] = _tune.serve_workload(
                "sketch_apply", "CWT", "float32", (64, 8), 16,
                cap, rowwise=False)
            buckets[f"jlt_rw_64x128_s32/b{cap}"] = _tune.serve_workload(
                "sketch_apply", "JLT", "float32", (64, 128), 32,
                cap, rowwise=True)
            cap *= 2
        outcomes = {}
        for bname, w in buckets.items():
            plan, _cost = _tune.record_ranked(w)
            modeled = {}
            for p, c in _tune.rank_candidates(w):
                modeled.setdefault(
                    p.backend,
                    {"modeled_s": float(f"{c['modeled_s']:.3g}"),
                     "interpret_penalized": bool(c.get("interpret"))})
            ent = _tune.get_cache().entry(w)
            outcomes[bname] = {
                "selected": plan.backend,
                "source": ent["source"] if ent else None,
                "candidates": modeled,
            }

        def kab_run(exk):
            futs = ([exk.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                     for (T, A) in cwt_reqs]
                    + [exk.submit_sketch(T, A, dimension=sk.ROWWISE)
                       for (T, A) in jlt_reqs])
            outs = [f.result(timeout=60) for f in futs]
            jax.block_until_ready(outs)
            return outs

        def kab_measure(kernel):
            exk = engine.MicrobatchExecutor(
                max_batch=kab_batch, linger_us=5000,
                max_queue=8 * kab_nreq, kernel=kernel)
            # warm every pow2 capacity class of both buckets up front —
            # same provably-compile-free discipline as warm_capacities
            # above: a linger-fragmented straggler cohort in the
            # measured window must never hit a cold capacity class
            cap = 1
            while cap <= kab_batch:
                futs = ([exk.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                         for (T, A) in cwt_reqs[:cap]]
                        + [exk.submit_sketch(T, A, dimension=sk.ROWWISE)
                           for (T, A) in jlt_reqs[:cap]])
                exk.flush()
                jax.block_until_ready(
                    [f.result(timeout=120) for f in futs])
                cap *= 2
            kab_run(exk)                   # warm both buckets
            m0 = engine.stats().misses
            r0 = engine.stats().recompiles
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                outs = kab_run(exk)
                best = min(best, time.perf_counter() - t0)
            st_k = exk.stats()["kernel"]["by_backend"]
            exk.shutdown()
            return (2 * kab_nreq / best, outs,
                    engine.stats().misses - m0,
                    engine.stats().recompiles - r0, st_k)

        rps_sel, out_sel, m_sel, r_sel, flushes_sel = kab_measure(None)
        rps_xla, out_xla, _mx, _rx, _fx = kab_measure("xla")
        kab_equal = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(out_sel, out_xla))
        kab_close = all(
            np.allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                        atol=1e-5)
            for a, b in zip(out_sel, out_xla))
    finally:
        _tune.set_cache(prev_cache)

    on_tpu = jax.default_backend() == "tpu"
    kernel_ab = {
        "buckets": outcomes,
        "rps_selected": round(rps_sel, 1),
        "rps_forced_xla": round(rps_xla, 1),
        "speedup_selected_vs_xla": round(rps_sel / rps_xla, 2),
        "selected_flushes_by_backend": {
            k: v["flushes"] for k, v in flushes_sel.items()},
        "misses_after_warmup": m_sel,
        "recompiles_after_warmup": r_sel,
        "bit_equal_to_forced_xla": kab_equal,
        "allclose_to_forced_xla": kab_close,
        "note": None if on_tpu else (
            "CPU host: the tuner correctly certifies XLA for every "
            "serve bucket (interpret-mode pallas is a correctness "
            "surface, not a speed surface — cost.INTERPRET_PENALTY); "
            "the pallas side of this A/B only opens up on real "
            "silicon, where numbers ride the committed-record protocol "
            "(bench tunnel dead since r02 — ROADMAP)"),
    }

    rec = {
        "metric": "serve_microbatch_throughput",
        "platform": jax.default_backend(),
        "n_requests": n_requests,
        "max_batch": max_batch,
        "rps_batched": round(rps_bat, 1),
        "rps_sequential": round(rps_seq, 1),
        "speedup": round(rps_bat / rps_seq, 2),
        "bit_equal_to_capacity1_dispatch": lane_equal,
        "allclose_to_exact_sequential": close,
        # compiles across the measured window: zero proves steady-state
        # traffic never leaves the per-bucket warmed executables
        "misses_after_warmup": measured_misses,
        "recompiles_after_warmup": measured_recompiles,
        "padding_waste_ratio": st["padding_waste_ratio"],
        "batch_capacity_hist": st["batch_capacity_hist"],
        "latency_ms": {
            "p50": round(st["latency_s"]["p50"] * 1e3, 3)
            if st["latency_s"]["p50"] is not None else None,
            "p99": round(st["latency_s"]["p99"] * 1e3, 3)
            if st["latency_s"]["p99"] is not None else None,
        },
        "endpoints": {"solve_l2_sketched": solve_ab,
                      "krr_predict": krr_ab},
        "kernel_ab": kernel_ab,
        "degraded_mode": degraded_mode,
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    _ledger_append("serve_microbatch_rps_batched", rec["rps_batched"])


# ---------------------------------------------------------------------------
# cache-level measurement: content-addressed hot-operand storm A/B
# ---------------------------------------------------------------------------


def _cache(n_requests: int = 240, n_unique: int = 4,
           max_batch: int = 8, rounds: int = 5) -> None:
    """Content-addressed result-cache A/B (``python bench.py --cache``;
    backend-agnostic — run with JAX_PLATFORMS=cpu for the hardware-free
    record; docs/caching).

    Workload: a **hot-operand storm** — ``n_requests`` submits cycling
    ``n_unique`` distinct (transform, operand) requests, each unique
    request under its own Context seed (same bucket class, different
    content address). *Uncached* runs the storm through a plain
    microbatch executor: every duplicate re-flushes. *Cached* runs the
    identical storm with ``cache=True``: the uniques compute once at
    warmup and the measured window is pure digest→result hits — zero
    flushes, zero compiles, bit-equal results. A single-flight leg
    storms one digest concurrently and proves one miss + N-1 coalesced
    futures off ONE flush. Prints exactly one JSON line and appends
    the headline to ``benchmarks/ledger.json``."""
    import jax
    import numpy as np

    from libskylark_tpu import Context, engine
    from libskylark_tpu import sketch as sk

    engine.reset()
    rng = np.random.default_rng(0)
    s_dim = 64
    uniq = []
    for i in range(n_unique):
        T = sk.JLT(256, s_dim, Context(seed=i))
        A = rng.standard_normal((256, 24)).astype(np.float32)
        uniq.append((T, A))

    def storm(ex):
        futs = [ex.submit_sketch(*uniq[i % n_unique],
                                 dimension=sk.COLUMNWISE)
                for i in range(n_requests)]
        outs = [f.result(timeout=60) for f in futs]
        jax.block_until_ready(outs)
        return outs

    def measure(ex):
        best = float("inf")
        outs = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            outs = storm(ex)
            best = min(best, time.perf_counter() - t0)
        return n_requests / best, outs

    # -- uncached control: every duplicate re-flushes -------------------
    ex0 = engine.MicrobatchExecutor(
        max_batch=max_batch, linger_us=2000,
        max_queue=4 * n_requests, workers=2, cache=False)
    # warm every pow2 capacity class so the measured window is
    # provably compile-free however linger fragments the cohorts
    cap = 1
    while cap <= max_batch:
        futs = [ex0.submit_sketch(*uniq[i % n_unique],
                                  dimension=sk.COLUMNWISE)
                for i in range(cap)]
        ex0.flush()
        jax.block_until_ready([f.result(timeout=120) for f in futs])
        cap *= 2
    storm(ex0)
    st = engine.stats()
    warm0 = (st.misses, st.recompiles)
    rps_uncached, out_uncached = measure(ex0)
    u_misses = engine.stats().misses - warm0[0]
    u_recompiles = engine.stats().recompiles - warm0[1]
    flushes_uncached = ex0.stats()["flushes"]
    ex0.shutdown()

    # -- cached: uniques compute once, the storm is pure hits -----------
    ex1 = engine.MicrobatchExecutor(
        max_batch=max_batch, linger_us=2000,
        max_queue=4 * n_requests, workers=2, cache=True)
    for T, A in uniq:                     # one flush per unique
        ex1.submit_sketch(T, A, dimension=sk.COLUMNWISE)\
            .result(timeout=120)
    # the settle callback inserts from the flush worker AFTER the
    # future resolves — barrier on the entry count so the measured
    # storm cannot race the last warm insert into a spurious miss
    deadline = time.monotonic() + 30
    while (ex1.stats()["cache"]["entries"] < n_unique
           and time.monotonic() < deadline):
        time.sleep(0.001)
    flushes_warm = ex1.stats()["flushes"]
    st = engine.stats()
    warm1 = (st.misses, st.recompiles)
    rps_cached, out_cached = measure(ex1)
    c_misses = engine.stats().misses - warm1[0]
    c_recompiles = engine.stats().recompiles - warm1[1]
    cache_blk = ex1.stats()["cache"]
    flushes_measured = ex1.stats()["flushes"] - flushes_warm

    bit_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(out_cached, out_uncached))

    # -- single-flight leg: one digest stormed concurrently -------------
    ex2 = engine.MicrobatchExecutor(max_batch=max_batch,
                                    linger_us=500_000,
                                    max_queue=4 * n_requests,
                                    cache=True)
    sf_n = 64
    futs = [ex2.submit_sketch(*uniq[0], dimension=sk.COLUMNWISE)
            for _ in range(sf_n)]
    ex2.flush()
    sf_outs = [np.asarray(f.result(timeout=120)) for f in futs]
    sf_blk = ex2.stats()["cache"]
    single_flight = {
        "concurrent_submits": sf_n,
        "flushes": ex2.stats()["flushes"],
        "misses": sf_blk["misses"],
        "coalesced": sf_blk["single_flight_coalesced"],
        "fan_bit_equal": all(np.array_equal(o, sf_outs[0])
                             for o in sf_outs[1:]),
    }
    ex1.shutdown()
    ex2.shutdown()

    rec = {
        "metric": "cache_hot_operand_storm",
        "platform": jax.default_backend(),
        "n_requests": n_requests,
        "unique_requests": n_unique,
        "max_batch": max_batch,
        "rps_cached": round(rps_cached, 1),
        "rps_uncached": round(rps_uncached, 1),
        "speedup": round(rps_cached / rps_uncached, 2),
        "bit_equal_to_uncached": bit_equal,
        "cached_flushes_measured": flushes_measured,
        "uncached_flushes": flushes_uncached,
        # compiles across both measured windows: zero proves the A/B
        # compares dispatch paths, not compilation luck
        "misses_after_warmup": {"cached": c_misses,
                                "uncached": u_misses},
        "recompiles_after_warmup": {"cached": c_recompiles,
                                    "uncached": u_recompiles},
        "cache": {
            "hit_rate": cache_blk["hit_rate"],
            "hits": cache_blk["hits"],
            "misses": cache_blk["misses"],
            "bytes_saved": cache_blk["bytes_saved"],
            "entries": cache_blk["entries"],
        },
        "single_flight": single_flight,
        "host_cores": os.cpu_count(),
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    _ledger_append("cache_hot_storm_speedup", rec["speedup"])
    ok = (rec["speedup"] >= 3.0
          and bit_equal
          and flushes_measured == 0
          and not (c_misses or c_recompiles
                   or u_misses or u_recompiles)
          and single_flight["misses"] == 1
          and single_flight["coalesced"] == sf_n - 1
          and single_flight["fan_bit_equal"])
    if not ok:
        sys.exit(1)


def _net(n_requests: int = 160, n_unique: int = 4,
         max_batch: int = 8, rounds: int = 5) -> None:
    """Loopback-TCP vs in-process front-door A/B (``python bench.py
    --net``; backend-agnostic — run with JAX_PLATFORMS=cpu for the
    hardware-free record; docs/networking).

    Workload: the cache bench's hot-operand storm, submitted twice
    against the SAME warmed 2-replica fleet — once through
    ``Router.submit_sketch`` in-process, once through a
    :class:`~libskylark_tpu.net.NetClient` over a loopback TCP
    :class:`~libskylark_tpu.net.NetServer`. Both measured windows are
    pure cache hits (zero flushes, zero compiles), so the rps delta
    is exactly the wire tax: framing, the tagged codec, two socket
    hops, and the server's dispatch thread. Results must be
    bit-equal across the wire. Prints exactly one JSON line and
    appends the loopback headline to ``benchmarks/ledger.json``."""
    import jax
    import numpy as np

    from libskylark_tpu import Context, engine, fleet, net
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.engine import resultcache as rc

    engine.reset()
    rng = np.random.default_rng(0)
    s_dim = 64
    uniq = []
    for i in range(n_unique):
        T = sk.JLT(256, s_dim, Context(seed=i))
        A = rng.standard_normal((256, 24)).astype(np.float32)
        uniq.append((T, A))

    def fleet_entries(pool):
        blocks = [pool.get(n).executor.stats().get("cache")
                  for n in pool.names()]
        return rc.merge_cache_blocks(
            [b for b in blocks if b])["entries"]

    pool = fleet.ReplicaPool(2, max_batch=max_batch, linger_us=2000,
                             cache=True)
    router = fleet.Router(pool, cache=True)
    srv = net.NetServer(router)
    client = net.NetClient(srv.address, seed=0)
    try:
        # warmup: one flush per unique, then barrier on the entry
        # count so neither measured window can race the last warm
        # insert into a spurious flush
        for T, A in uniq:
            router.submit_sketch(T, A).result(timeout=120)
        deadline = time.monotonic() + 30
        while (fleet_entries(pool) < n_unique
               and time.monotonic() < deadline):
            time.sleep(0.001)
        # one loopback round-trip per unique warms the client's
        # connection and the codec paths
        for T, A in uniq:
            client.submit("sketch_apply", transform=T, A=A,
                          dimension=sk.COLUMNWISE).result(timeout=120)

        def storm_inproc():
            futs = [router.submit_sketch(*uniq[i % n_unique])
                    for i in range(n_requests)]
            return [np.asarray(f.result(timeout=120)) for f in futs]

        def storm_loopback():
            futs = [client.submit(
                "sketch_apply", transform=uniq[i % n_unique][0],
                A=uniq[i % n_unique][1], dimension=sk.COLUMNWISE)
                for i in range(n_requests)]
            return [np.asarray(f.result(timeout=120)) for f in futs]

        def measure(storm):
            best = float("inf")
            outs = None
            for _ in range(rounds):
                t0 = time.perf_counter()
                outs = storm()
                best = min(best, time.perf_counter() - t0)
            return n_requests / best, outs

        st = engine.stats()
        warm = (st.misses, st.recompiles)
        rps_inproc, out_inproc = measure(storm_inproc)
        rps_loopback, out_loopback = measure(storm_loopback)
        st = engine.stats()
        compiles = (st.misses - warm[0], st.recompiles - warm[1])
        bit_equal = all(
            np.array_equal(a, b)
            for a, b in zip(out_loopback, out_inproc))
        ns = srv.stats()
        rec = {
            "metric": "net_loopback_vs_inprocess",
            "platform": jax.default_backend(),
            "n_requests": n_requests,
            "unique_requests": n_unique,
            "rps_inprocess": round(rps_inproc, 1),
            "rps_loopback": round(rps_loopback, 1),
            "wire_tax_ratio": round(rps_loopback / rps_inproc, 3),
            "bit_equal_to_inprocess": bit_equal,
            # compiles across both measured windows: zero proves the
            # A/B compares transport paths, not compilation luck
            "compiles_measured": {"misses": compiles[0],
                                  "recompiles": compiles[1]},
            "server": {
                "requests": ns["requests"],
                "wire_errors": ns["wire_errors"],
                "bytes_in": ns["bytes_in"],
                "bytes_out": ns["bytes_out"],
                "retries_represented": ns["retries_represented"],
            },
            "host_cores": os.cpu_count(),
            "telemetry": _telemetry_snapshot(),
        }
    finally:
        client.close()
        srv.close()
        router.close()
        pool.shutdown()
    print(json.dumps(rec), flush=True)
    _ledger_append("net_loopback_hot_rps", rec["rps_loopback"])
    ok = (bit_equal
          and compiles == (0, 0)
          and rec["server"]["wire_errors"] == 0
          and rps_loopback > 0)
    if not ok:
        sys.exit(1)


# ---------------------------------------------------------------------------
# fleet-level measurement: N-replica router vs single executor
# ---------------------------------------------------------------------------


def _fleet_process_leg(host_cores: int, n_requests: int = 64,
                       max_batch: int = 16, n_proc: int = 2,
                       rounds: int = 3) -> tuple:
    """Process replicas as the production fleet shape: pack-booted
    children (zero compiles), SHM operand/result transport, hedged
    requests — A/B'd against a same-workload thread fleet. Returns
    ``(record, ab_gate)``; see ``_fleet``'s docstring."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from libskylark_tpu import Context, engine, fleet
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.engine import warmup

    # the leg's results are ~8-30 KB: drop the SHM threshold below
    # them so BOTH directions demonstrably ride the rings (env writes
    # are legal; every read goes through the registry)
    os.environ["SKYLARK_FLEET_SHM_MIN_BYTES"] = "4096"

    # two pow2 classes (ragged rows AND ragged contracted dims inside
    # each padding class): with bounded-load affinity each of the two
    # replicas owns one class, so the fleets actually parallelize
    pclasses = ({"n_lo": 112, "s": 32}, {"n_lo": 52, "s": 32})
    rng = np.random.default_rng(1)
    ctx = Context(seed=0)
    reqs = []
    for i in range(n_requests):
        c = pclasses[i % 2]
        n = c["n_lo"] + (i % 3) * 4
        m = 48 + (i % 4) * 4
        T = sk.JLT(n, c["s"], ctx)
        A = rng.standard_normal((m, n)).astype(np.float32)
        reqs.append((T, A))

    def storm(submit):
        futs = [submit(T, A) for (T, A) in reqs]
        outs = [f.result(timeout=300) for f in futs]
        jax.block_until_ready(outs)
        return outs

    def measure(submit):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            storm(submit)
            best = min(best, time.perf_counter() - t0)
        return n_requests / best

    # -- thread-fleet baseline, same workload --------------------------
    engine.reset()
    host_workers = max(2, min(n_proc, host_cores))
    tpool = fleet.ReplicaPool(n_proc, max_batch=max_batch,
                              linger_us=5000,
                              max_queue=4 * n_requests,
                              shared_workers=host_workers)
    trouter = fleet.Router(tpool)
    tsubmit = lambda T, A: trouter.submit_sketch(  # noqa: E731
        T, A, dimension=sk.ROWWISE)
    storm(tsubmit)
    storm(tsubmit)
    rps_thread = measure(tsubmit)
    trouter.close()
    tpool.shutdown()

    # -- process fleet: pack boot + SHM + hedging ----------------------
    caps = []
    cap = 1
    while cap <= max_batch:
        caps.append(cap)
        cap *= 2
    pack_dir = tempfile.mkdtemp(prefix="skylark_fleet_pack_")
    try:
        specs = [warmup.BucketSpec(
            endpoint="sketch_apply", family="JLT", n=c["n_lo"], m=60,
            s_dim=c["s"], rowwise=True, capacities=tuple(caps))
            for c in pclasses]
        manifest = warmup.build_pack(pack_dir, specs)
        pool = fleet.ReplicaPool(n_proc, backend="process",
                                 warmup_pack=pack_dir,
                                 max_batch=max_batch, linger_us=5000,
                                 max_queue=4 * n_requests)
        router = fleet.Router(pool, hedge=True)
        submit = lambda T, A: router.submit_sketch(  # noqa: E731
            T, A, dimension=sk.ROWWISE)
        storm(submit)               # settle queues/hedge-delay samples
        rps_process = measure(submit)
        # bit-equality: routed-over-SHM results vs capacity-1 dispatch
        b_out = storm(submit)
        ex1 = engine.MicrobatchExecutor(max_batch=1, linger_us=100)
        bit_equal = all(
            np.array_equal(
                np.asarray(b),
                np.asarray(ex1.submit_sketch(T, A,
                                             dimension=sk.ROWWISE)
                           .result(timeout=300)))
            for b, (T, A) in zip(b_out, reqs))
        ex1.shutdown()
        # the children's own word on what they booted with and what
        # their payloads rode on — AFTER the traffic, so the compile
        # counter covers the whole leg
        boots = {name: pool.get(name).boot_info()
                 for name in pool.names()}
        compiles_children = sum(
            (b.get("engine") or {}).get("compiles", 0)
            for b in boots.values())
        aot_loads_children = sum(
            (b.get("engine") or {}).get("aot_loads", 0)
            for b in boots.values())
        shm_children = {name: (b.get("shm") or {})
                        for name, b in boots.items()}
        shm_parent = {name: pool.get(name).transport_stats()
                      for name in pool.names()}
        hstats = router.stats()
        router.close()
        pool.shutdown()
    finally:
        shutil.rmtree(pack_dir, ignore_errors=True)
        os.environ.pop("SKYLARK_FLEET_SHM_MIN_BYTES", None)

    rec = {
        "n_proc": n_proc,
        "workload_classes": [
            {"rows": "48..60", "cols": f"{c['n_lo']}..{c['n_lo'] + 8}",
             "s_dim": c["s"]} for c in pclasses],
        "rps_process_fleet": round(rps_process, 1),
        "rps_thread_fleet": round(rps_thread, 1),
        "process_vs_thread": round(rps_process / rps_thread, 2),
        "pack_entries": len(manifest.get("entries", [])),
        "compiles_children_total": compiles_children,
        "aot_loads_children_total": aot_loads_children,
        "bit_equal_to_capacity1_dispatch": bit_equal,
        "shm_parent": shm_parent,
        "shm_children": shm_children,
        "hedged": hstats["hedged"],
        "hedge_wins": hstats["hedge_wins"],
        "hedge_mismatches": hstats["hedge_mismatches"],
        "leaked_shm_entries": fleet.shm_entries(),
    }
    ab_gate = {
        "checked": host_cores >= 4,
        "passed": (bool(rps_process > rps_thread)
                   if host_cores >= 4 else None),
        "rule": "on >=4-core hosts the process fleet must beat the "
                "same-workload thread fleet (regression = bench "
                "failure, not a warning)",
    }
    return rec, ab_gate


def _fleet_autoscale_episode() -> dict:
    """A short storm -> scale-up -> idle -> scale-down round trip on a
    thread pool, so the committed record's telemetry snapshot carries
    the live ``fleet.autoscale_*`` counters (the full contract is
    gated by benchmarks/fleet_smoke.py's autoscale leg)."""
    import numpy as np

    from libskylark_tpu import Context, fleet
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.resilience import faults

    rng = np.random.default_rng(2)
    ctx = Context(seed=0)
    T = sk.CWT(40, 16, ctx)
    ops = [rng.standard_normal((40, 3 + i % 4)).astype(np.float32)
           for i in range(16)]
    pool = fleet.ReplicaPool(1, max_batch=8, linger_us=2000)
    router = fleet.Router(pool)
    scaler = fleet.Autoscaler(pool, router, min_replicas=1,
                              max_replicas=2, up_depth=2, down_depth=1,
                              up_ticks=1, down_ticks=4,
                              cooldown_s=0.3, interval_s=0.05)
    failures = 0
    try:
        for A in ops[:4]:
            router.submit_sketch(T, A).result(timeout=120)
        plan = {"seed": 4, "faults": [
            {"site": "serve.flush", "stall_s": 0.01, "every": 1}]}
        with faults.fault_plan(plan):
            futs = [router.submit_sketch(T, A)
                    for A in ops for _ in range(4)]
            deadline = time.monotonic() + 20
            while (time.monotonic() < deadline
                   and len(pool.names()) < 2):
                time.sleep(0.05)
            for f in futs:
                try:
                    f.result(timeout=120)
                except Exception:  # noqa: BLE001 — counted
                    failures += 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(pool.names()) > 1:
            time.sleep(0.1)
        st = scaler.stats()
        return {
            "scale_ups": st["scale_ups"],
            "scale_downs": st["scale_downs"],
            "replicas_final": len(pool.names()),
            "client_visible_failures": failures,
        }
    finally:
        scaler.close()
        router.close()
        pool.shutdown()


def _fleet(n_requests: int = 64, n_replicas: int = 4,
           max_batch: int = 16, rounds: int = 5) -> None:
    """Replicated-fleet throughput A/B (``python bench.py --fleet``;
    backend-agnostic — run with JAX_PLATFORMS=cpu for the
    hardware-free record).

    Workload: ``n_requests`` in-flight ragged sketch-apply requests
    over four distinct pow2 bucket classes — a heterogeneous mix
    spanning the ``--serve`` record's exact class ((48..60)x(112..128),
    s=32) plus the lighter classes of the microbatching sweet spot
    (``engine/bucket.py``'s design point: floods of small ragged
    requests). *Fleet* routes them over ``n_replicas`` in-process
    replicas through the warm-cache-aware ``fleet.Router`` — bounded-
    load sticky affinity gives each replica one class, so the classes
    flush concurrently on four executors while the fleet's total
    compile count stays equal to a single executor's. The same storm
    is also measured on ONE ``MicrobatchExecutor`` (at the r8
    ``--serve`` config, workers=2, and at thread parity with the
    fleet) — the in-run A/B — and the committed ``--serve`` record's
    single-executor throughput is read for the cross-record
    comparison. All sides are fully warmed; the record carries the
    engine miss/recompile deltas over the measured window (zero
    proves the warm replicas never compiled) and the router's
    affinity hit-rate.

    Host caveat the record states explicitly: on a host with fewer
    cores than one executor's workers can saturate (the 2-core CI
    box), in-process replication cannot raise aggregate throughput —
    every replica shares one GIL and one core budget, so the fleet's
    in-run numbers trail the single executor by the coordination tax
    while buying per-replica drain/failover; the throughput upside
    needs per-replica cores (or process-backed replicas).

    The drain leg then preempts one replica MID-STORM (the per-replica
    SIGTERM story: drain + router failover) and records the
    client-visible failure count — the acceptance criterion is zero —
    plus the surviving fleet's throughput.

    The **process leg** then runs the production many-core shape: a
    2-class storm over process replicas booted warm from a freshly
    built r13 warmup pack (zero backend compiles in every child —
    asserted from ``boot_info``), operands and results riding the
    shared-memory transport (``fleet/shm``), hedged requests enabled,
    measured against a same-workload thread-replica fleet. The record
    carries ``host_cores`` and an ``ab_gate`` verdict: on hosts with
    >= 4 cores a process fleet slower than the thread fleet FAILS the
    bench (exit 1), not just warns — parity is a regression there. On
    smaller hosts the record stays honest (host_note) without
    failing: with every replica pinned to the same single core, a
    spawned interpreter per replica cannot beat a shared one. A short
    thread-pool autoscale episode (storm -> scale-up -> idle ->
    scale-down) runs last so the embedded telemetry snapshot carries
    the ``fleet.autoscale_*`` counters alongside the hedge counters.
    Prints one JSON line."""
    import threading as _threading

    import jax
    import numpy as np

    from libskylark_tpu import Context, engine, fleet
    from libskylark_tpu import sketch as sk

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)

    # four distinct bucket classes (statics differ by padded shape
    # and/or sketch dim): the --serve record's class plus three
    # lighter sweet-spot classes; ragged rows inside one row class
    # (48..60 -> 64)
    classes = (
        {"n_lo": 20, "s": 16},     # pad 32, s 16
        {"n_lo": 52, "s": 16},     # pad 64, s 16
        {"n_lo": 112, "s": 32},    # pad 128, s 32 — the --serve class
        {"n_lo": 52, "s": 32},     # pad 64, s 32
    )
    reqs = []
    for i in range(n_requests):
        c = classes[i % len(classes)]
        n = c["n_lo"] + (i % 3) * 4
        m = 48 + (i % 4) * 4
        T = sk.JLT(n, c["s"], ctx)
        A = rng.standard_normal((m, n)).astype(np.float32)
        reqs.append((T, A))

    engine.reset()

    def storm(submit):
        futs = [submit(T, A) for (T, A) in reqs]
        outs = [f.result(timeout=120) for f in futs]
        jax.block_until_ready(outs)
        return outs

    def measure(submit):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            storm(submit)
            best = min(best, time.perf_counter() - t0)
        return n_requests / best

    def warm_ladder(submit):
        """Compile every (class, pow2 capacity) executable up front so
        the measured window is provably compile-free no matter how the
        linger deadline fragments a round's cohorts (affinity keeps
        each class's ladder on its owner when routed)."""
        for c_idx in range(len(classes)):
            idxs = [i for i in range(n_requests)
                    if i % len(classes) == c_idx]
            cap = 1
            while cap <= max_batch:
                futs = [submit(*reqs[i]) for i in idxs[:cap]]
                jax.block_until_ready(
                    [f.result(timeout=120) for f in futs])
                cap *= 2

    def single_rps(workers: int) -> float:
        ex = engine.MicrobatchExecutor(max_batch=max_batch,
                                       linger_us=5000,
                                       max_queue=4 * n_requests,
                                       workers=workers,
                                       name=f"bench-single-w{workers}")
        submit = lambda T, A: ex.submit_sketch(T, A,  # noqa: E731
                                               dimension=sk.ROWWISE)
        warm_ladder(submit)
        storm(submit)
        rps = measure(submit)
        ex.shutdown()
        return rps

    rps_single_w2 = single_rps(2)      # the r8 --serve lineage config
    rps_single_par = single_rps(n_replicas)   # thread parity

    # -- fleet: N replicas, affinity-routed, host-sized flush pool -----
    # shared_workers sizes flush concurrency to the host: N replicas
    # each running private workers would run N concurrent flushes and
    # thrash a small host's cores (docs/fleet, "Tuning N")
    host_workers = max(2, min(n_replicas, os.cpu_count() or 2))
    pool = fleet.ReplicaPool(n_replicas, max_batch=max_batch,
                             linger_us=5000, max_queue=4 * n_requests,
                             shared_workers=host_workers)
    router = fleet.Router(pool)
    submit = lambda T, A: router.submit_sketch(  # noqa: E731
        T, A, dimension=sk.ROWWISE)
    warm_ladder(submit)
    storm(submit)
    st = engine.stats()
    warm = (st.misses, st.recompiles)
    r0 = router.stats()
    rps_fleet = measure(submit)
    r1 = router.stats()
    measured_misses = engine.stats().misses - warm[0]
    measured_recompiles = engine.stats().recompiles - warm[1]
    routed_delta = r1["routed"] - r0["routed"]
    affinity_rate = (
        round((r1["affinity_hit"] - r0["affinity_hit"]) / routed_delta, 4)
        if routed_delta else None)

    # correctness spot-check: routed results equal a capacity-1 serve
    # dispatch bitwise (lane invariance holds THROUGH the router)
    b_out = storm(submit)
    ex1 = engine.MicrobatchExecutor(max_batch=1, linger_us=100)
    lane_equal = all(
        np.array_equal(
            np.asarray(b),
            np.asarray(ex1.submit_sketch(T, A, dimension=sk.ROWWISE)
                       .result(timeout=120)))
        for b, (T, A) in zip(b_out, reqs))
    ex1.shutdown()

    # -- drain leg: preempt one replica mid-storm ----------------------
    victim = router.owner_of("sketch_apply", transform=reqs[0][0],
                             A=reqs[0][1], dimension=sk.ROWWISE)
    fired_hooks = []
    pool.on_replica_drain(victim, lambda: fired_hooks.append(victim))
    barrier = _threading.Event()
    preempted = {}

    def preempt():
        barrier.wait(10.0)
        preempted["drained"] = pool.preempt_replica(victim, timeout=60)

    t = _threading.Thread(target=preempt)
    t.start()
    drain_failures = 0
    futs = []
    for i, (T, A) in enumerate(reqs):
        futs.append(submit(T, A))
        if i == n_requests // 4:
            barrier.set()              # SIGTERM-equivalent lands here
    t.join()
    for f in futs:
        try:
            jax.block_until_ready(f.result(timeout=120))
        except Exception:  # noqa: BLE001 — counted, not fatal
            drain_failures += 1
    rps_after_drain = measure(submit)
    r2 = router.stats()

    drain = {
        "victim": victim,
        "drained_to_quiescence": bool(preempted.get("drained")),
        "final_drain_hook_fired": fired_hooks == [victim],
        "client_visible_failures": drain_failures,
        "routable_after": r2["routable"],
        "failovers": r2["failover"],
        "rps_fleet_after_drain": round(rps_after_drain, 1),
    }

    router.close()
    pool.shutdown()

    # -- process leg: pack-booted process replicas + SHM + hedging -----
    host_cores = os.cpu_count() or 1
    proc_rec, ab_gate = _fleet_process_leg(
        host_cores, n_requests=n_requests, max_batch=max_batch)

    # -- autoscale episode: counters into the telemetry snapshot -------
    autoscale_rec = _fleet_autoscale_episode()

    # cross-record comparison: the committed single-executor --serve
    # record (rps_batched at 64 in-flight) — regenerated by the same
    # CI pipeline the fleet gate runs in, so the two records share a
    # machine and an era
    serve_record = None
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "benchmarks",
                               "results_serve_cpu.json")) as fh:
            serve_rec = json.loads(fh.read().strip().splitlines()[-1])
        serve_record = {
            "rps_batched": serve_rec.get("rps_batched"),
            "n_requests": serve_rec.get("n_requests"),
            "file": "benchmarks/results_serve_cpu.json",
        }
    except Exception:  # noqa: BLE001 — record beats perfect record
        pass

    rps_single = max(rps_single_w2, rps_single_par)
    best_rps = max(rps_fleet,
                   proc_rec.get("rps_process_fleet") or 0.0)
    rec = {
        "metric": "fleet_router_throughput",
        "platform": jax.default_backend(),
        "host_cores": host_cores,
        "n_requests": n_requests,
        "n_replicas": n_replicas,
        "max_batch": max_batch,
        "workload_classes": [
            {"rows": "48..60", "cols": f"{c['n_lo']}..{c['n_lo'] + 8}",
             "s_dim": c["s"]} for c in classes
        ],
        "rps_fleet": round(rps_fleet, 1),
        "rps_single_inrun_workers2": round(rps_single_w2, 1),
        "rps_single_inrun_thread_parity": round(rps_single_par, 1),
        "fleet_vs_single_inrun": round(rps_fleet / rps_single, 2),
        "single_executor_serve_record": serve_record,
        "fleet_exceeds_serve_record": (
            bool(best_rps > serve_record["rps_batched"])
            if serve_record and serve_record.get("rps_batched")
            else None),
        "host_note": (
            f"measured on a {host_cores}-core host. "
            + ("process replicas have their own cores here, so the "
               "A/B gate below is enforced: the process fleet must "
               "beat the thread fleet."
               if host_cores >= 4 else
               "with fewer than 4 cores every replica — thread or "
               "process — shares the same core budget, so neither "
               "fleet shape can beat an equally-warmed single "
               "executor; the process leg still proves the transport "
               "(SHM, zero-compile pack boot, hedging) and the A/B "
               "gate records without failing. The throughput "
               "multiple needs per-replica cores.")),
        "affinity_hit_rate_measured_window": affinity_rate,
        "routed_by_replica": r1["by_replica"],
        "misses_after_warmup": measured_misses,
        "recompiles_after_warmup": measured_recompiles,
        "bit_equal_to_capacity1_dispatch": lane_equal,
        "drain": drain,
        "process": proc_rec,
        "ab_gate": ab_gate,
        "autoscale": autoscale_rec,
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    if ab_gate["checked"] and not ab_gate["passed"]:
        print("fleet A/B FAILED on a >=4-core host: "
              f"process fleet {proc_rec.get('rps_process_fleet')} rps "
              f"did not beat thread fleet "
              f"{proc_rec.get('rps_thread_fleet')} rps",
              file=sys.stderr)
        sys.exit(1)


# ---------------------------------------------------------------------------
# boot-level measurement: cold-start A/B, warmup pack vs fresh compile
# ---------------------------------------------------------------------------


def _boot(capacity: int = 16) -> None:
    """Fleet-boot cold-start A/B (``python bench.py --boot``;
    backend-agnostic — run with JAX_PLATFORMS=cpu for the hardware-free
    record).

    Builds a 2-bucket warmup pack (the ``--serve`` record's JLT class
    plus a CWT class) in-process, then boots two FRESH python
    processes serving the same canonical cohorts — one loading the
    pack (``skylark_warmup boot-probe``), one compiling cold — and
    records wall-from-spawn time-to-first-result for both, the warm
    side's zero-backend-compile proof (``compiles == 0`` with every
    executable arriving as an ``aot_load``), and bit-equality of both
    sides against the builder's in-process results. Prints exactly one
    JSON line."""
    import shutil
    import tempfile

    from libskylark_tpu.engine import warmup

    pack = tempfile.mkdtemp(prefix="skylark_boot_pack_")
    try:
        specs = [
            # the --serve record's class: JLT rowwise (48..60)x(112..128)
            # -> pad (64, 128), s=32
            warmup.BucketSpec(endpoint="sketch_apply", family="JLT",
                              n=128, m=60, s_dim=32, rowwise=True,
                              capacities=(capacity,)),
            warmup.BucketSpec(endpoint="sketch_apply", family="CWT",
                              n=112, m=12, s_dim=32, rowwise=False,
                              capacities=(capacity,)),
        ]
        manifest = warmup.build_pack(pack, specs)

        # fresh children via the one shared launcher (hermetic env
        # scrub included), so the bench record and the CI boot gate
        # (benchmarks/boot_smoke.py) always measure the same thing
        cold = warmup.spawn_boot_probe(pack, load=False)
        warm = warmup.spawn_boot_probe(pack, load=True)
    finally:
        shutil.rmtree(pack, ignore_errors=True)

    ttfr_cold = cold.get("wall_since_spawn_s")
    ttfr_warm = warm.get("wall_since_spawn_s")
    rec = {
        "metric": "fleet_boot_cold_start",
        "entries": len(manifest["entries"]),
        "capacity": capacity,
        "ttfr_cold_s": ttfr_cold,
        "ttfr_pack_s": ttfr_warm,
        "speedup_ttfr": (round(ttfr_cold / ttfr_warm, 4)
                         if ttfr_cold and ttfr_warm else None),
        "serve_wall_cold_s": cold.get("t_total_s"),
        "serve_wall_pack_s": warm.get("t_total_s"),
        "compiles_cold": cold["engine"]["compiles"],
        "compile_seconds_cold": cold["engine"]["compile_seconds"],
        "compiles_pack": warm["engine"]["compiles"],
        "aot_loads_pack": warm["engine"]["aot_loads"],
        "load_seconds_pack": warm["engine"]["load_seconds"],
        "bit_equal_cold": cold["bit_equal"],
        "bit_equal_pack": warm["bit_equal"],
        "pack_loaded": (warm.get("warmup") or {}).get("loaded"),
        "plan_fingerprint": manifest["plan_fingerprint"],
        "backend": manifest["compat"]["backend"],
        "host_note": (
            "wall-from-spawn includes interpreter + jax import, which "
            "both sides pay equally; the pack side replaces the "
            "per-bucket XLA compiles with artifact deserializes "
            "(compile_seconds vs load_seconds above)"),
    }
    rec["telemetry"] = _telemetry_snapshot()
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# sparse-operand serve measurement: CSR lanes vs densify-then-sketch
# ---------------------------------------------------------------------------


def _sparse(n_requests: int = 32, max_batch: int = 8,
            rounds: int = 5, n_dim: int = 4096, m_dim: int = 16,
            density: float = 0.01) -> None:
    """Sparse serve A/B (``python bench.py --sparse``;
    backend-agnostic — run with JAX_PLATFORMS=cpu for the hardware-free
    record committed at ``benchmarks/results_sparse_cpu.json``).

    Workload: ``n_requests`` in-flight CSR requests at ``density`` on a
    (n_dim, m_dim) operand class, ragged nnz inside ONE pow2 nnz
    class. *Sparse* submits the CSR lanes through ``submit_sparse``
    (the O(nnz) scatter flush); *densify* is the status quo this PR
    retires — the client densifies each operand host-side and submits
    it through the dense sketch endpoint (O(N·m) segment-sum flush +
    the dense host stacking bytes). Both sides are fully warmed; the
    record carries the engine's miss/recompile deltas across the
    measured window (zero after per-bucket warmup) and the sparse
    results' bit-equality against the densified reference — the CSR
    lanes accumulate in the dense scatter's row-major order, so the
    speedup is free of any numerics trade. A JLT row rides along: its
    sparse flush densifies *in-executable* (same matmul bits), so its
    win is the avoided host densify + dense-operand stacking only.
    Prints exactly one JSON line."""
    import jax
    import numpy as np
    import scipy.sparse as sp

    from libskylark_tpu import Context, engine
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.engine import bucket as bucketing

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    s_dim = 32
    cells = n_dim * m_dim

    def rand_sparse(nnz):
        r = rng.integers(0, n_dim, nnz)
        c = rng.integers(0, m_dim, nnz)
        v = rng.standard_normal(nnz).astype(np.float32)
        return SparseMatrix.from_scipy(
            sp.coo_matrix((v, (r, c)), shape=(n_dim, m_dim)))

    base_nnz = max(int(cells * density), 8)
    engine.reset()

    def family_ab(T, reqs, dense_ops):
        ex = engine.MicrobatchExecutor(max_batch=max_batch,
                                       linger_us=5000,
                                       max_queue=8 * n_requests)

        def warm(submit_one):
            cap = 1
            while cap <= max_batch:
                futs = [submit_one(i) for i in range(cap)]
                ex.flush()
                jax.block_until_ready(
                    [f.result(timeout=120) for f in futs])
                cap *= 2

        def run(submit_one):
            futs = [submit_one(i) for i in range(len(reqs))]
            outs = [f.result(timeout=120) for f in futs]
            jax.block_until_ready(outs)
            return outs

        sparse_submit = lambda i: ex.submit_sparse(  # noqa: E731
            T, reqs[i], dimension=sk.COLUMNWISE)
        # densify-then-sketch: the client pays toarray() per submit —
        # that IS the status-quo cost this path removes, so it stays
        # inside the measured window
        dense_submit = lambda i: ex.submit_sketch(  # noqa: E731
            T, dense_ops[i], dimension=sk.COLUMNWISE)

        warm(sparse_submit)
        warm(dense_submit)
        s_out = run(sparse_submit)
        d_out = run(dense_submit)
        m0, r0 = engine.stats().misses, engine.stats().recompiles
        best_s = best_d = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            run(sparse_submit)
            best_s = min(best_s, time.perf_counter() - t0)
            t0 = time.perf_counter()
            run(lambda i: ex.submit_sketch(
                T, np.asarray(reqs[i].to_scipy().toarray(),
                              dtype=np.float32),
                dimension=sk.COLUMNWISE))
            best_d = min(best_d, time.perf_counter() - t0)
        misses = engine.stats().misses - m0
        recompiles = engine.stats().recompiles - r0
        bit_equal = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(s_out, d_out))
        # capacity-1 lane invariance of the sparse path
        ex1 = engine.MicrobatchExecutor(max_batch=1, linger_us=100)
        lane_equal = all(
            np.array_equal(
                np.asarray(a),
                np.asarray(ex1.submit_sparse(
                    T, A, dimension=sk.COLUMNWISE).result(timeout=120)))
            for a, A in zip(s_out, reqs))
        ex1.shutdown()
        st = ex.stats()
        ex.shutdown()
        return {
            "rps_sparse": round(len(reqs) / best_s, 1),
            "rps_densify": round(len(reqs) / best_d, 1),
            "speedup_sparse_vs_densify": round(best_d / best_s, 2),
            "bit_equal_to_densified_reference": bit_equal,
            "bit_equal_to_capacity1_dispatch": lane_equal,
            "misses_after_warmup": misses,
            "recompiles_after_warmup": recompiles,
            "sparse_stats": st["sparse"],
        }

    # ragged nnz inside ONE pow2 class: base .. base + 7·base/16 stays
    # under the next class boundary, so the whole storm coalesces into
    # a single bucket (the zero-recompile window depends on it)
    reqs_cwt = [rand_sparse(base_nnz + (i % 8) * (base_nnz // 16))
                for i in range(n_requests)]
    dense_cwt = [np.asarray(A.to_scipy().toarray(), dtype=np.float32)
                 for A in reqs_cwt]
    T_cwt = sk.CWT(n_dim, s_dim, ctx)
    cwt = family_ab(T_cwt, reqs_cwt, dense_cwt)

    reqs_jlt = [rand_sparse(base_nnz + (i % 8) * (base_nnz // 16))
                for i in range(n_requests)]
    dense_jlt = [np.asarray(A.to_scipy().toarray(), dtype=np.float32)
                 for A in reqs_jlt]
    T_jlt = sk.JLT(n_dim, s_dim, ctx)
    jlt = family_ab(T_jlt, reqs_jlt, dense_jlt)

    rec = {
        "metric": "serve_sparse_throughput",
        "platform": jax.default_backend(),
        "n_requests": n_requests,
        "max_batch": max_batch,
        "operand": {"shape": [n_dim, m_dim], "density": density,
                    "nnz_base": base_nnz,
                    "nnz_class": bucketing.nnz_class(base_nnz)},
        "endpoints": {"cwt_sketch_apply": cwt,
                      "jlt_sketch_apply": jlt},
        "note": (
            "CWT is where sparsity pays: O(nnz) scatter vs the dense "
            "path's O(N*m) segment-sum. The JLT sparse flush "
            "densifies in-executable (bit-equal matmul), so its edge "
            "is only the avoided host densify + dense stacking; "
            "kernel-level sparse wins (pallas_sparse) open up on "
            "real silicon via bench.py --certify-kernels."),
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)


def _fwht(n_requests: int = 8, max_batch: int = 4, rounds: int = 5,
          s_dim: int = 256, m_dim: int = 8,
          n_dims=(4096, 16384, 65536)) -> None:
    """Panel vs panel-free SRHT A/B (``python bench.py --fwht``;
    backend-agnostic — run with JAX_PLATFORMS=cpu for the
    hardware-free record).

    Two legs, one per retired panel path:

    - **fold leg** (the dist-shard / session-append contraction): per
      ``n`` in ``n_dims``, contract an integer-lattice ``(n, m)``
      operand through the SRHT operator both ways — *panel* generates
      the O(n·s) Sylvester-Hadamard column panel and pays the
      O(n·s·m) GEMM (the status quo this PR retires, regenerated per
      fold exactly as the shard tasks and streaming appenders did);
      *panel-free* is ``FJLT.fold_rows``, the O(n·log n·m) in-place
      FWHT fold. Operands are dyadic (integer lattice, ``n``/``s``
      even powers of two), so the two sides must be **bit-equal** —
      the speedup is free of any numerics trade. The largest-``n``
      speedup is appended to ``benchmarks/ledger.json`` as
      ``fwht_panel_free_speedup`` (the CI fwht gate requires ≥ 1.3);
    - **serve leg**: an ``n_requests`` SRHT storm through the
      microbatch executor's panel-free flush, fully warmed — the
      measured window must show ZERO engine cache misses and ZERO
      recompiles, and every served result must be bit-equal to the
      ``A @ panel.T`` oracle.

    Prints exactly one JSON line; exits nonzero on any violation."""
    import jax
    import numpy as np

    from libskylark_tpu import Context, engine
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.sketch.fjlt import FJLT

    rng = np.random.default_rng(0)
    violations = []

    # -- fold leg: O(n·s) panel + GEMM vs O(n·log n·m) FWHT fold --------
    folds = {}
    for n in n_dims:
        t = FJLT(n, s_dim, Context(seed=n), fut="wht")
        X = rng.integers(-4, 5, (n, m_dim)).astype(np.float32)

        def panel_fold():
            # panel regenerated per fold — that IS the per-shard /
            # per-append cost the panel-free path removes, so it
            # stays inside the measured window
            P = np.asarray(t.operator_panel(0, n))
            return P @ X

        def free_fold():
            return np.asarray(t.fold_rows(X, 0, n))

        p_out, f_out = panel_fold(), free_fold()
        if not np.array_equal(p_out, f_out):
            violations.append(
                f"fold n={n}: panel-free fold not bit-equal to the "
                "panel contraction on dyadic operands")
        best_p = best_f = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            panel_fold()
            best_p = min(best_p, time.perf_counter() - t0)
            t0 = time.perf_counter()
            free_fold()
            best_f = min(best_f, time.perf_counter() - t0)
        folds[str(n)] = {
            "panel_s": round(best_p, 4),
            "panel_free_s": round(best_f, 4),
            "speedup": round(best_p / best_f, 2),
            "bit_equal": bool(np.array_equal(p_out, f_out)),
        }
    top_n = str(max(n_dims))
    speedup = folds[top_n]["speedup"]
    if speedup < 1.3:
        violations.append(
            f"fold n={top_n}: panel-free speedup {speedup} below the "
            "1.3x acceptance floor")

    # -- serve leg: warmed panel-free storm, zero-compile window --------
    engine.reset()
    n_srv = n_dims[0]
    ts = [FJLT(n_srv, s_dim, Context(seed=i), fut="wht")
          for i in range(n_requests)]
    ops = [rng.integers(-4, 5, (m_dim, n_srv)).astype(np.float32)
           for _ in range(n_requests)]
    ex = engine.MicrobatchExecutor(max_batch=max_batch, linger_us=5000,
                                   max_queue=8 * n_requests)

    def storm():
        futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                for t, A in zip(ts, ops)]
        outs = [f.result(timeout=300) for f in futs]
        jax.block_until_ready(outs)
        return outs

    cap = 1
    while cap <= max_batch:
        futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                for t, A in zip(ts[:cap], ops[:cap])]
        ex.flush()
        [f.result(timeout=300) for f in futs]
        cap *= 2
    storm()
    m0, r0 = engine.stats().misses, engine.stats().recompiles
    outs = storm()
    best_storm = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        storm()
        best_storm = min(best_storm, time.perf_counter() - t0)
    misses = engine.stats().misses - m0
    recompiles = engine.stats().recompiles - r0
    fwht_stats = ex.stats()["fwht"]
    ex.shutdown()
    if misses:
        violations.append(
            f"{misses} engine cache miss(es) in the measured window")
    if recompiles:
        violations.append(
            f"{recompiles} recompile(s) in the measured window")
    if not fwht_stats["by_backend"]:
        violations.append("no SRHT flushes attributed on the serve leg")
    for i, o in enumerate(outs):
        P = np.asarray(ts[i].operator_panel(0, n_srv))
        if not np.array_equal(np.asarray(o), ops[i] @ P.T):
            violations.append(
                f"serve request {i}: panel-free flush not bit-equal "
                "to the A @ panel.T oracle")
            break

    rec = {
        "metric": "fwht_panel_free_speedup",
        "value": speedup,
        "platform": jax.default_backend(),
        "s_dim": s_dim,
        "m_dim": m_dim,
        "fold_ab": folds,
        "serve": {
            "n_dim": n_srv,
            "rps": round(n_requests / best_storm, 1),
            "misses_after_warmup": misses,
            "recompiles_after_warmup": recompiles,
            "flushes_by_backend": {
                k: v["flushes"]
                for k, v in fwht_stats["by_backend"].items()},
        },
        "violations": violations,
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    if violations:
        sys.exit(1)
    _ledger_append("fwht_panel_free_speedup", speedup)


# ---------------------------------------------------------------------------
# dist-serve measurement: pipelined shard fan-out A/B + cost calibration
# ---------------------------------------------------------------------------


def _dist_serve(n_requests: int = 4, n_replicas: int = 4,
                rounds: int = 3, n_rows: int = 50_000, d_dim: int = 128,
                s_dim: int = 128, shard_rows: int = 6_250) -> None:
    """Pipelined dist-serve fan-out A/B (``python bench.py
    --dist-serve``; backend-agnostic — run with JAX_PLATFORMS=cpu for
    the hardware-free record).

    Two legs over the same large row-sharded operand (``n_rows`` ×
    ``d_dim``, non-pow2 row count, 8 shard tasks per request):

    - **single leg**: ``submit_dist_sketch`` on one fleet-less
      executor at ``pipeline=1`` — the serialized single-executor
      status quo (one shard at a time, local compute);
    - **dist leg**: ``Router.submit_dist_sketch`` over an
      ``n_replicas``-thread fleet — shard tasks fanned through the
      ring with pipelined dispatch, partials merged incrementally as
      they land.

    Every request uses a FRESH plan seed (the content-addressed cache
    would otherwise serve round 2+ for free and the "throughput" would
    be a cache benchmark); plan shapes are identical so the measured
    window is fully warmed — ZERO engine cache misses and ZERO
    recompiles required. Round-0 dist results must be **bit-equal** to
    the one-shot ``sketch_local`` oracle at coverage 1.0 (the
    canonical merge tree is associativity-exact, not approximately
    equal). The ledger records
    (``benchmarks/ledger.json``) are honest about host class: on a
    1-core CPU host thread-fan-out cannot beat serialized compute —
    the CI gate ratchets against the best PRIOR record of the SAME
    host class (≥ 0.5×), and the ≥ 2x acceptance target is a
    multi-core/fleet-host expectation, not this host's.

    Also times the XLA scatter-add retire rate (the ``segment_sum``
    microbench) and appends it as ``cost_calib_scatter_rows_per_s`` —
    the measured constant ``tune.cost.effective_rates`` overlays on
    the analytic roofline for this host class
    (``SKYLARK_COST_CALIB``).

    Prints exactly one JSON line; exits nonzero on any violation."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import dist as _dist
    from libskylark_tpu import engine, fleet
    from libskylark_tpu import tune as _tune
    from libskylark_tpu.dist import plan as _dplan

    rng = np.random.default_rng(0)
    violations = []

    X = rng.standard_normal((n_rows, d_dim)).astype(np.float32)
    source = _dist.ArraySource(X)

    def make_plan(seed: int):
        return _dplan.ShardPlan(
            kind="jlt", n=n_rows, s_dim=s_dim, d=d_dim, seed=seed,
            shard_rows=shard_rows).validate()

    # fresh seeds per round and leg: the result cache must never serve
    # a measured request (leg A/B stays a compute benchmark)
    seed_iter = iter(range(1000, 100_000))

    def storm(submit, n: int):
        futs = [submit(make_plan(next(seed_iter))) for _ in range(n)]
        return [f.result(timeout=600) for f in futs]

    # -- single leg: fleet-less executor, serialized shard loop ---------
    engine.reset()
    ex = engine.MicrobatchExecutor(max_batch=4)
    storm(lambda p: ex.submit_dist_sketch(p, source, pipeline=1), 1)
    m0, r0 = engine.stats().misses, engine.stats().recompiles
    best_single = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        storm(lambda p: ex.submit_dist_sketch(p, source, pipeline=1),
              n_requests)
        best_single = min(best_single, time.perf_counter() - t0)
    single_misses = engine.stats().misses - m0
    single_recompiles = engine.stats().recompiles - r0
    ex.shutdown()

    # -- dist leg: router fan-out over an n_replicas thread fleet -------
    pool = fleet.ReplicaPool(n_replicas, backend="thread")
    router = fleet.Router(pool)
    try:
        storm(lambda p: router.submit_dist_sketch(p, source), 1)
        fan0 = {k: v.get("shard_tasks", 0) for k, v in
                engine.serve_stats()["dist"]["by_replica"].items()}
        m0, r0 = engine.stats().misses, engine.stats().recompiles
        first = None
        best_dist = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            outs = storm(
                lambda p: router.submit_dist_sketch(p, source),
                n_requests)
            best_dist = min(best_dist, time.perf_counter() - t0)
            if first is None:
                first = outs
        dist_misses = engine.stats().misses - m0
        dist_recompiles = engine.stats().recompiles - r0
        # window-scoped fan-out: serve_stats aggregates every executor
        # in the process, so diff out the single leg's "<local>" tasks
        fanout = {k: v.get("shard_tasks", 0) - fan0.get(k, 0)
                  for k, v in
                  engine.serve_stats()["dist"]["by_replica"].items()}
        fanout = {k: v for k, v in sorted(fanout.items()) if v > 0}
    finally:
        router.close()
        pool.shutdown()

    # -- proofs: coverage 1.0, bit-equal merge, warmed window -----------
    for res in first:
        if res.coverage != 1.0 or res.degraded:
            violations.append(
                f"dist result degraded: coverage {res.coverage}")
            break
    # the round-0 seeds of the dist leg are deterministic:
    # 1 (single warm) + rounds*n_requests (single) + 1 (dist warm)
    base_seed = 1000 + 1 + rounds * n_requests + 1
    for i, res in enumerate(first):
        oracle = _dplan.sketch_local(make_plan(base_seed + i), source)
        if not np.array_equal(np.asarray(res.SX),
                              np.asarray(oracle.SX)):
            violations.append(
                f"dist request {i}: merged sketch not bit-equal to "
                "the one-shot sketch_local oracle")
            break
    for leg, msd, rcd in (("single", single_misses, single_recompiles),
                          ("dist", dist_misses, dist_recompiles)):
        if msd:
            violations.append(f"{leg} leg: {msd} engine cache "
                              "miss(es) in the measured window")
        if rcd:
            violations.append(f"{leg} leg: {rcd} recompile(s) in the "
                              "measured window")
    if sum(1 for v in fanout.values() if v > 0) < 2:
        violations.append(
            f"shard fan-out degenerate: by_replica {fanout}")

    rows_s_single = n_rows * n_requests / best_single
    rows_s_dist = n_rows * n_requests / best_dist
    speedup = round(rows_s_dist / rows_s_single, 3)

    # -- cost calibration: measured scatter-add retire rate -------------
    n_sc, s_sc = 1 << 18, 512
    seg = jnp.asarray(rng.integers(0, s_sc, n_sc, dtype=np.int32))
    Xs = jnp.asarray(
        rng.standard_normal((n_sc, 8)).astype(np.float32))
    scat = jax.jit(lambda x, g: jax.ops.segment_sum(
        x, g, num_segments=s_sc))
    scat(Xs, seg).block_until_ready()
    best_sc = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        scat(Xs, seg).block_until_ready()
        best_sc = min(best_sc, time.perf_counter() - t0)
    scatter_rate = n_sc / best_sc

    rec = {
        "metric": "dist_serve_fanout_speedup",
        "value": speedup,
        "platform": jax.default_backend(),
        "host_cores": os.cpu_count(),
        "operand": {"n": n_rows, "d": d_dim, "s_dim": s_dim,
                    "shards": make_plan(0).num_shards},
        "single": {"rows_per_s": round(rows_s_single, 1),
                   "best_s": round(best_single, 4)},
        "dist": {"rows_per_s": round(rows_s_dist, 1),
                 "best_s": round(best_dist, 4),
                 "replicas": n_replicas,
                 "shard_fanout": fanout},
        "cost_calibration": {
            "scatter_rows_per_s": round(scatter_rate, 1),
            "analytic_scatter_rows_per_s":
                _tune.RATES["scatter_rows_per_s"],
        },
        "violations": violations,
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)
    if violations:
        sys.exit(1)
    # calibration first, headline last: CI gates key off the ledger
    # tail, and the dist gate reads the LAST dist_serve record
    _ledger_append("cost_calib_scatter_rows_per_s",
                   round(scatter_rate, 1))
    _ledger_append("dist_serve_fanout_speedup", speedup)


# ---------------------------------------------------------------------------
# kernel certification: measured (not ranked) plan-cache entries
# ---------------------------------------------------------------------------


def _certify_kernels(rounds: int = 5, capacity: int = 8) -> None:
    """One-shot serve-ladder certification job (``python bench.py
    --certify-kernels``): measure the Pallas-vs-XLA batched-flush
    ladder per representative serve bucket — dense (JLT), hash (CWT),
    fastfood, the sparse-CSR family, and the panel-free SRHT/FWHT
    tier — and feed the winners into
    the plan cache as **measured** entries, upgrading the r12 "ranked"
    (cost-model) decisions into recorded chip-level outcomes
    (``tune.record_measurement``: measured entries displace ranked
    ones and are only ever replaced by better measurements).

    Hardware truth is part of the record: the job first runs a bounded
    ``--probe`` subprocess and embeds the structured ``probe_health``
    block. Plan-cache writes happen ONLY when the probe is live AND
    this process is on a TPU backend — on a CPU host (the dead-tunnel
    status quo, ROADMAP) the job still runs end to end, timing the XLA
    side and recording an honest ``interpret-mode/tunnel-dead`` block,
    but writes nothing: interpret-mode pallas timings are a
    correctness surface, not a speed surface, and must never be
    recorded as chip measurements. Prints exactly one JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import tune
    from libskylark_tpu.sketch import (pallas_dense, pallas_fastfood,
                                       pallas_fwht, pallas_hash,
                                       pallas_sparse)

    ph = probe_health_block(run_probe=True)
    on_tpu = jax.default_backend() == "tpu"
    live = bool(on_tpu and ph.get("status") == "live"
                and ph.get("platform") == "tpu")
    if not live and ph.get("status") == "live" \
            and ph.get("platform") != "tpu":
        # the probe subprocess came back on a non-TPU backend (the
        # JAX_PLATFORMS=cpu hardware-free run): a reachable CPU is not
        # a live tunnel — say so instead of leaving a bare "live"
        ph = dict(ph)
        ph["reason"] = (f"probe reached backend "
                        f"{ph.get('platform')!r}, not a TPU — tunnel "
                        "dead for certification purposes "
                        "(interpret-mode only)")

    rng = np.random.default_rng(0)
    import jax.random as jr

    def keys(n):
        return np.stack([
            np.asarray(jr.key_data(jr.PRNGKey(i)), dtype=np.uint32)
            for i in range(n)])

    def time_flush(fn):
        """Best wall seconds of one batched flush over ``rounds``
        (compile excluded by a warmup call); None when the candidate
        raises (Mosaic rejection = a decline, recorded as such)."""
        try:
            jax.block_until_ready(fn())
        except Exception as e:  # noqa: BLE001 — decline, don't fail
            return None, repr(e)[:160]
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best, None

    buckets = {}

    # -- hash family: CWT columnwise (64, 8) s16 -------------------------
    kd = keys(capacity)
    A = rng.standard_normal((capacity, 64, 8)).astype(np.float32)
    Aj = jnp.asarray(A)
    w = tune.serve_workload("sketch_apply", "CWT", "float32", (64, 8),
                            16, capacity, rowwise=False)
    from libskylark_tpu.sketch.hash import cwt_serve_apply

    xla_cwt = jax.jit(jax.vmap(
        lambda k, a: cwt_serve_apply(k, a, s_dim=16, rowwise=False)))
    cands = {
        "xla": lambda: xla_cwt(kd, Aj),
        "pallas": (lambda: pallas_hash.cwt_apply_batched(
            kd, Aj, s_dim=16, rowwise=False, accum="mxu"))
        if live else None,
    }
    buckets["cwt_cw_64x8_s16"] = (w, cands)

    # -- dense family: JLT rowwise (64, 128) s32 -------------------------
    kd2 = keys(capacity)
    A2 = jnp.asarray(
        rng.standard_normal((capacity, 64, 128)).astype(np.float32))
    sc2 = jnp.asarray(np.full((capacity,), 0.17677669529663687,
                              np.float32))
    w2 = tune.serve_workload("sketch_apply", "JLT", "float32",
                             (64, 128), 32, capacity, rowwise=True)
    from libskylark_tpu.base import randgen
    from libskylark_tpu.sketch.dense import serve_apply

    xla_jlt = jax.jit(jax.vmap(
        lambda k, s, a: serve_apply(k, s, a, dist=randgen.Normal(),
                                    s_dim=32, rowwise=True)))
    cands2 = {
        "xla": lambda: xla_jlt(kd2, sc2, A2),
        "pallas": (lambda: pallas_dense.serve_batched_apply(
            kd2, sc2, A2, dist=randgen.Normal(), s_dim=32,
            rowwise=True)) if live else None,
    }
    buckets["jlt_rw_64x128_s32"] = (w2, cands2)

    # -- fastfood family: (16, 16) s32 ------------------------------------
    kd3 = keys(capacity)
    A3 = jnp.asarray(
        rng.standard_normal((capacity, 16, 16)).astype(np.float32))
    w3 = tune.serve_workload("fastfood_features", "FastGaussianRFT",
                             "float32", (16, 16), 32, capacity)
    from libskylark_tpu.sketch.frft import fastfood_serve_apply

    xla_ff = jax.jit(jax.vmap(
        lambda k, a: fastfood_serve_apply(
            k, a, n_dim=16, s_dim=32, fut="wht",
            sm_kind="gauss", sm_param=1.0)))
    cands3 = {
        "xla": lambda: xla_ff(kd3, A3),
        "pallas": (lambda: pallas_fastfood.serve_features_batched(
            kd3, A3, n_dim=16, s_dim=32, fut="wht",
            sm_kind="gauss", sm_param=1.0)) if live else None,
    }
    buckets["fastfood_16x16_s32"] = (w3, cands3)

    # -- sparse family: CWT columnwise (4096, 16) s32, nnz class 1024 ----
    nnz_cls, n_sp, m_sp = 1024, 4096, 16
    kd4 = keys(capacity)
    data = rng.standard_normal(
        (capacity, nnz_cls)).astype(np.float32)
    rows = rng.integers(0, n_sp, (capacity, nnz_cls)).astype(np.int32)
    rows.sort(axis=1)                       # CSR row-major discipline
    cols = rng.integers(0, m_sp, (capacity, nnz_cls)).astype(np.int32)
    w4 = tune.serve_workload("sparse_sketch_apply", "CWT", "float32",
                             (n_sp, m_sp), 32, capacity, rowwise=False,
                             nnz=nnz_cls)
    from libskylark_tpu.sketch import sparse_serve as _ssrv

    # the XLA side runs the serve program proper (indptr lanes); build
    # indptr from the sorted rows so both candidates see one operand
    ptr = np.zeros((capacity, n_sp + 1), np.int32)
    for b in range(capacity):
        ptr[b] = np.searchsorted(rows[b], np.arange(n_sp + 1))
    ptrj, dataj, colsj = (jnp.asarray(ptr), jnp.asarray(data),
                          jnp.asarray(cols))
    kd4j = jnp.asarray(kd4)
    xla_sp = jax.jit(jax.vmap(
        lambda k, d, ix, p: _ssrv.cwt_sparse_serve_apply(
            k, d, ix, p, s_dim=32, rowwise=False,
            shape=(n_sp, m_sp))))
    cands4 = {
        "xla": lambda: xla_sp(kd4j, dataj, colsj, ptrj),
        "pallas": (lambda: pallas_sparse.cwt_sparse_apply_batched(
            kd4, dataj, jnp.asarray(rows), colsj, s_dim=32,
            rowwise=False, shape=(n_sp, m_sp), accum="mxu"))
        if live else None,
    }
    buckets["sparse_cwt_cw_4096x16_s32_z1024"] = (w4, cands4)

    # -- SRHT family: panel-free FWHT rowwise (8, 4096) s256 -------------
    kd5 = keys(capacity)
    A5 = jnp.asarray(
        rng.integers(-4, 5, (capacity, 8, 4096)).astype(np.float32))
    w5 = tune.serve_workload("sketch_apply", "SRHT", "float32",
                             (8, 4096), 256, capacity, rowwise=True)
    from libskylark_tpu.sketch.fjlt import srht_serve_apply

    xla_srht = jax.jit(jax.vmap(
        lambda k, a: srht_serve_apply(k, a, s_dim=256, rowwise=True)))
    cands5 = {
        "xla": lambda: xla_srht(kd5, A5),
        "pallas": (lambda: pallas_fwht.srht_apply_batched(
            kd5, A5, s_dim=256, rowwise=True)) if live else None,
    }
    buckets["srht_rw_8x4096_s256"] = (w5, cands5)

    results = {}
    upgraded = 0
    for bname, (w, cands) in buckets.items():
        row = {"workload": w.key(), "candidates": {}}
        prior = tune.get_cache().entry(w)
        row["prior"] = ({"source": prior.get("source"),
                         "backend": (prior.get("plan") or {})
                         .get("backend")} if prior else None)
        best = None
        for backend, fn in cands.items():
            if fn is None:
                row["candidates"][backend] = {
                    "status": "skipped",
                    "reason": ("no live TPU: interpret-mode pallas is "
                               "a correctness surface, not a speed "
                               "surface")}
                continue
            secs, err = time_flush(fn)
            if secs is None:
                row["candidates"][backend] = {"status": "declined",
                                              "reason": err}
                continue
            fps = 1.0 / secs
            row["candidates"][backend] = {
                "status": "measured" if live else "timed",
                "flushes_per_s": round(fps, 2)}
            if best is None or fps > best[1]:
                best = (backend, fps)
        if best is not None:
            row["winner"] = best[0]
            if live:
                from libskylark_tpu.tune.plans import Plan

                plan = (Plan("pallas") if best[0] == "pallas"
                        else Plan("xla"))
                changed = tune.record_measurement(
                    w, plan, best[1], unit="flushes/s",
                    extra={"certified_by": "bench.py --certify-kernels",
                           "capacity": capacity})
                row["cache_write"] = ("measured" if changed
                                      else "kept-better-measurement")
                upgraded += int(changed)
            else:
                row["cache_write"] = (
                    "none (probe not live on a TPU backend — "
                    "measured entries require chip truth)")
        results[bname] = row

    rec = {
        "metric": "kernel_certification",
        "platform": jax.default_backend(),
        "live_tpu": live,
        "capacity": capacity,
        "rounds": rounds,
        "measured_entries_written": upgraded,
        "plan_cache_path": tune.get_cache().path,
        "buckets": results,
        "probe_health": ph,
        "telemetry": _telemetry_snapshot(),
    }
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# parent: bounded orchestration
# ---------------------------------------------------------------------------


def _sub(arg: str, timeout: float):
    """Run this script with ``arg`` in a subprocess; (rc, stdout+stderr)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), arg],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return -1, f"TIMEOUT after {timeout}s\n{out}"


def _previous_value() -> float | None:
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        mm = re.search(r"BENCH_r(\d+)\.json$", p)
        if not mm:
            continue
        try:
            with open(p) as fh:
                rec = json.load(fh)
            # driver-written files wrap the emitted record (top level is
            # {n, cmd, rc, tail}, record under "parsed" or embedded in the
            # "tail" text); accept any layout, skip null values
            value = rec.get("value", (rec.get("parsed") or {}).get("value"))
            if value is None and isinstance(rec.get("tail"), str):
                mt = re.search(
                    r'\{"metric": "%s".*?\}' % re.escape(METRIC),
                    rec["tail"])
                if mt:
                    value = json.loads(mt.group(0)).get("value")
            if value is None:
                continue
            rounds.append((int(mm.group(1)), float(value)))
        except Exception:
            continue
    return max(rounds)[1] if rounds else None


def _verify_committed(here: str, path: str, raw: str, rec: dict,
                      rnd: int) -> dict:
    """Validate the newest committed on-chip headline record so a wedged
    driver run reports a VERIFIED artifact instead of a bare null:
    sha256 of the record bytes (ties the reported number to one exact
    committed file), its provenance stamp, and whether the on-chip
    oracle certification stamp is (a) present for the same round and
    (b) FRESHER than the kernel source it certifies — a stale stamp
    means the kernel changed after certification and the number can't
    be tied to certified numerics."""
    import hashlib

    out = {
        "value": rec.get("value"),
        "unit": "GB/s",
        "file": os.path.relpath(path, here),
        "sha256": hashlib.sha256(raw.encode()).hexdigest(),
        "captured": (rec.get("provenance") or {}).get("captured"),
        "cold_start_wall_s": rec.get("cold_start_wall_s"),
    }
    stamp = os.path.join(here, "benchmarks",
                         f".tpu_oracle_recert_r{rnd:02d}")
    if os.path.exists(stamp):
        try:
            with open(stamp) as fh:
                out["oracle_stamp"] = fh.read().strip()
            # content identity over the kernel CLOSURE (pallas_dense +
            # params + randgen; _KERNEL_CLOSURE): a stamp certifying
            # only pallas_dense.py — the pre-closure format — is stale
            # by policy, because a params/randgen change after
            # certification would otherwise ride it (ADVICE r5; mtimes
            # are not preserved by git checkouts, so content hashes are
            # the only meaningful freshness signal)
            out["oracle_fresh"] = _stamp_fresh_against(
                out["oracle_stamp"], here)
            if (not out["oracle_fresh"]
                    and "closure_sha256=" not in out["oracle_stamp"]):
                out["oracle_stale_reason"] = (
                    "pre-closure stamp format (kernel_sha256 only); "
                    "re-certify with `python bench.py --stamp`")
        except Exception:
            out["oracle_fresh"] = False
    else:
        out["oracle_stamp"] = None
        out["oracle_fresh"] = False
    return out


def _telemetry_snapshot():
    """The unified registry snapshot every benchmarks record embeds, so
    BENCH_*.json trajectories carry the cache/serve/resilience/tune/io
    counters alongside the timings (docs/observability). Collectors
    report with telemetry disabled too — they re-home counters the
    subsystems maintain anyway — so this costs nothing extra in the
    default (telemetry-off) bench run. Never raises."""
    try:
        from libskylark_tpu import telemetry

        return telemetry.snapshot()
    except Exception:  # noqa: BLE001 — a record beats a perfect record
        return None


def _emit(value, extra):
    prev = _previous_value()
    if value is None:
        vs = None          # no measurement → no ratio (not a fake 1.0)
    elif prev:
        vs = round(value / prev, 4)
    else:
        vs = 1.0
    rec = {
        "metric": METRIC,
        "value": value,
        "unit": "GB/s",
        "vs_baseline": vs,
    }
    rec.update(extra)
    rec["probe_health"] = probe_health_block()
    rec["telemetry"] = _telemetry_snapshot()
    print(json.dumps(rec), flush=True)


def main() -> None:
    t_start = time.monotonic()
    errors: list[str] = []

    # SKYLARK_BENCH_MAX_WALL: a hard wall budget below the retry
    # deadline — r4/r5 burned ~450s of escalating probe timeouts on a
    # dead tunnel before reaching the committed-capture fallback; the
    # budget caps the whole orchestration regardless of retry policy
    budget = DEADLINE
    mw = os.environ.get("SKYLARK_BENCH_MAX_WALL")
    if mw:
        try:
            budget = min(budget, float(mw))
        except ValueError:
            pass

    def time_left() -> float:
        return budget - (time.monotonic() - t_start)

    attempt = 0
    probe_timeout = PROBE_TIMEOUT
    while time_left() > 30:
        attempt += 1
        # Escalating probe timeouts; after two failed probes stop trusting
        # the probe entirely and spend the remaining budget on the
        # measurement child itself — a TPU that initializes slower than the
        # probe timeout (busy/recovering) is indistinguishable from a dead
        # one at probe level (r2: six 75s probes burned the whole deadline
        # and surfaced nothing).
        last_resort = attempt >= 3
        if last_resort:
            probe_ok, plat = True, "unprobed"
            _record_probe("skipped", None,
                          "probe distrusted after repeated failures; "
                          "spending remaining budget on the "
                          "measurement child", None)
        elif attempt == 1 and _fresh_stamp():
            # a content-fresh oracle stamp proves a live window recently
            # certified THIS kernel — skip the probe, spend the budget
            # on the measurement itself
            probe_ok, plat = True, "stamped"
            _record_probe("skipped", None,
                          "fresh oracle stamp: a live window already "
                          "certified this kernel", None)
        else:
            t_probe = time.monotonic()
            rc, out = _sub("--probe", min(probe_timeout, time_left() - 20))
            probe_latency = time.monotonic() - t_probe
            probe_ok = rc == 0 and "PROBE_OK" in out
            plat = (out.split("PROBE_OK", 1)[1].split()[0]
                    if probe_ok else "?")
            if probe_ok:
                _record_probe("live", plat, None, probe_latency)
            else:
                _record_probe(
                    "dead", None,
                    ("timeout" if rc == -1 else f"hard error rc={rc}")
                    + f": {out[-200:]}", probe_latency)
            probe_timeout = min(probe_timeout * 1.6, 180.0)
        if probe_ok:
            rc, out = _sub("--child", min(CHILD_TIMEOUT, time_left() - 10))
            # accept a printed result even if the child later timed out
            # (e.g. killed during the informational bf16 extra)
            mm = re.search(r"CHILD_RESULT (\{.*\})", out)
            if mm:
                rec = json.loads(mm.group(1))
                value = rec.pop("value")
                for me in re.findall(r"CHILD_EXTRA (\{.*\})", out):
                    rec.update(json.loads(me))
                if errors:
                    rec["retries"] = len(errors)
                _emit(value, rec)
                return
            errors.append(
                f"attempt {attempt}: probe {plat} but child failed "
                f"rc={rc}: {out[-300:]}"
            )
        else:
            errors.append(f"attempt {attempt}: probe failed rc={rc}: "
                          f"{out[-300:]}")
            if attempt == 1 and rc > 0:
                # fail-fast: the FIRST probe exited with a hard error
                # (backend init raised — unreachable/absent hardware),
                # not a timeout. Retrying cannot revive it; emit the
                # committed-capture record immediately instead of
                # burning the deadline on escalating probe timeouts.
                # Only rc > 0 qualifies: negative returncodes are
                # signal kills (OOM, SIGHUP — possibly transient) and
                # -1 is _sub's own timeout sentinel; both keep the
                # retry ladder.
                errors.append("fail-fast: backend unreachable on first "
                              "probe (hard error, not timeout); "
                              "skipping retries")
                break
        time.sleep(min(10.0, max(0.0, time_left() - 20)))

    extra = {"error": " | ".join(e.replace("\n", " ") for e in errors)
             or "deadline exhausted before any attempt"}
    # Surface the most recent committed on-chip measurement so a wedged
    # tunnel doesn't erase the round's evidence — as a STRUCTURED
    # verified-artifact block, not a bare null: the parent re-hashes the
    # committed record, carries its provenance timestamps, and checks the
    # on-chip oracle stamp is fresher than the kernel source it certifies
    # (the r3 verdict's verified-committed protocol for rounds whose
    # ~5-min live windows can't fit this script's cold start; the
    # watcher-measured cold-start wall time is in the record itself).
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        cands = []
        for pth in glob.glob(os.path.join(
                here, "benchmarks", "results_tpu_r*_headline.json")):
            mm = re.search(r"results_tpu_r(\d+)_headline\.json$", pth)
            if mm:
                cands.append((int(mm.group(1)), pth))
        if cands:
            rnd, path = max(cands)
            with open(path) as fh:
                raw = fh.read()
            rec = json.loads(raw)
            extra["last_measured_GBps"] = rec.get("value")
            extra["last_measured_file"] = os.path.basename(path)
            extra["verified_committed"] = _verify_committed(
                here, path, raw, rec, rnd)
        # the m-tile sweep may hold a BETTER committed measurement than
        # the defaults headline — surface the best row alongside
        best = None
        for pth in glob.glob(os.path.join(
                here, "benchmarks", "results_tpu_r*_mtile_sweep.jsonl")):
            with open(pth) as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    v = (row.get("rec") or {}).get("value")
                    if v is not None and (best is None or v > best[0]):
                        best = (v, {k: row[k] for k in
                                    ("m_tile", "pipeline", "precision")
                                    if k in row})
        if best is not None:
            extra["best_sweep_GBps"] = best[0]
            extra["best_sweep_config"] = best[1]
        # PROMOTION (r4 verdict #6): when the committed record is
        # content-verified against the kernel it certifies — the oracle
        # stamp carries the certified file's sha256 and it matches the
        # working tree — the watcher's capture IS this round's
        # measurement of this exact code; report its value rather than
        # a null. measured_live=false keeps the provenance honest: the
        # number was captured by the watcher inside a tunnel window and
        # validated here, not re-measured by this process.
        vc = extra.get("verified_committed") or {}
        if vc.get("oracle_fresh") and vc.get("value") is not None:
            extra["measured_live"] = False
            extra["promoted_from_committed"] = vc["file"]
            _emit(vc["value"], extra)
            return
    except Exception:
        pass
    _emit(None, extra)


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child()
    elif "--probe" in sys.argv:
        _probe()
    elif "--solver" in sys.argv:
        # solver-level engine measurement; backend-agnostic, in-process
        # (no wedge-proofing needed: run it with JAX_PLATFORMS=cpu for
        # the hardware-free record, or inside a live window for TPU)
        _solver()
    elif "--serve" in sys.argv:
        # microbatch serving throughput A/B (batched vs sequential
        # dispatch); backend-agnostic, in-process like --solver
        _serve()
    elif "--qos" in sys.argv:
        # multi-tenant QoS adaptive-vs-static batching A/B
        # (interactive p99 + zero-compile + bit-equality proof);
        # backend-agnostic, in-process like --serve
        _qos()
    elif "--fleet" in sys.argv:
        # N-replica router vs single-executor A/B + one-replica drain
        # failover; backend-agnostic, in-process like --serve
        _fleet()
    elif "--boot" in sys.argv:
        # fleet-boot cold-start A/B: fresh-process time-to-first-
        # result with vs without a warmup pack (zero-compile proof +
        # bit-equality); backend-agnostic
        _boot()
    elif "--sparse" in sys.argv:
        # sparse-operand serve A/B: CSR lanes vs densify-then-sketch
        # (bit-equality + zero-recompile proof); backend-agnostic
        _sparse()
    elif "--cache" in sys.argv:
        # content-addressed result-cache A/B: hot-operand storm,
        # cached vs uncached (bit-equality + zero-flush + single-
        # flight proof); backend-agnostic, in-process like --serve
        _cache()
    elif "--net" in sys.argv:
        # loopback-TCP vs in-process front-door A/B: hot cached storm
        # through NetClient/NetServer vs Router.submit (bit-equality +
        # zero-compile + zero-wire-error proof); backend-agnostic
        _net()
    elif "--fwht" in sys.argv:
        # panel vs panel-free SRHT A/B: FWHT fold vs O(n*s) panel
        # contraction (bit-equality + zero-compile proof + ledger
        # record); backend-agnostic
        _fwht()
    elif "--dist-serve" in sys.argv:
        # pipelined dist-serve fan-out A/B (router fleet vs serialized
        # single executor; bit-equality + coverage-1.0 + zero-recompile
        # proof) + the measured scatter-rate cost calibration record;
        # backend-agnostic
        _dist_serve()
    elif "--certify-kernels" in sys.argv:
        # one-shot serve-ladder certification: measure pallas-vs-XLA
        # per serve bucket and upgrade ranked plan-cache entries to
        # measured — cache writes only under a live TPU probe; on CPU
        # records an honest probe_health block and writes nothing
        _certify_kernels()
    elif "--stamp" in sys.argv:
        # the certification line for benchmarks/.tpu_oracle_recert_r*:
        # steps scripts append `$(python bench.py --stamp)` so the stamp
        # format can never drift from the verifier in this file
        print(_stamp_line())
    else:
        main()
