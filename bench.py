"""Headline benchmark: dense JLT sketch-apply throughput (GB/s/chip).

BASELINE.json config 1 scaled to saturate one chip: rowwise JLT apply
A·Sᵀ on a dense 8192×8192 matrix with sketch size 1024 (ref:
sketch/JLT.hpp + sketch/dense_transform_Elemental_local.hpp). The sketch
operator is generated on the fly from (seed, counter); on TPU the apply
runs through the fused Pallas generation+matmul kernel
(sketch/pallas_dense.py). Effective bytes = read(A) + write(SA); the
reference has no published numbers (BASELINE.md), so ``vs_baseline`` is
the ratio against the previous round's recorded value when a
BENCH_r*.json exists, else 1.0.

Each timed iteration consumes the FULL sketch output (the loop carries
sum(abs(SA)) back into the next input), so XLA cannot dead-code-eliminate
any part of the contraction; per-iteration time is the slope between a
2-iteration and a 12-iteration loop, cancelling dispatch/tunnel latency.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np


def run(m: int = 8192, n: int = 8192, s: int = 1024, repeats: int = 5):
    from jax import lax

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import JLT, ROWWISE
    from libskylark_tpu.sketch import pallas_dense as pd

    ctx = Context(seed=0)
    jlt = JLT(n, s, ctx)
    key = jlt._alloc.key
    use_pallas = pd.available()

    rng = np.random.default_rng(1)
    A = jax.device_put(jnp.asarray(
        rng.standard_normal((m, n), dtype=np.float32)))

    def one_apply(X):
        if use_pallas:
            out = pd.rowwise_apply(key, jlt.dist, X, s, jlt.scale)
            if out is not None:
                return out
        return jlt.apply(X, ROWWISE)

    def iterate(X, K):
        def body(_, acc):
            SA = one_apply(X + acc)
            # consume every element of SA; scale keeps the carry ~0 so the
            # input matrix is numerically unchanged between iterations
            return jnp.sum(jnp.abs(SA)).astype(jnp.float32) * 1e-37
        return lax.fori_loop(0, K, body, jnp.float32(0.0))

    k1, k2 = 2, 12
    f1 = jax.jit(lambda X: iterate(X, k1))
    f2 = jax.jit(lambda X: iterate(X, k2))
    float(f1(A))  # compile + warm
    float(f2(A))

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f1(A))
        t1 = time.perf_counter()
        float(f2(A))
        t2 = time.perf_counter()
        best = min(best, ((t2 - t1) - (t1 - t0)) / (k2 - k1))

    bytes_moved = 4 * (m * n + m * s)
    return bytes_moved / best / 1e9, best


def _previous_value() -> float | None:
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        mm = re.search(r"BENCH_r(\d+)\.json$", p)
        if not mm:
            continue
        try:
            with open(p) as fh:
                rec = json.load(fh)
            rounds.append((int(mm.group(1)), float(rec["value"])))
        except Exception:
            continue
    return max(rounds)[1] if rounds else None


def main():
    gbps, secs = run()
    prev = _previous_value()
    vs = gbps / prev if prev else 1.0
    print(json.dumps({
        "metric": "jlt_sketch_apply_GBps_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
