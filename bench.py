"""Headline benchmark: dense JLT sketch-apply throughput (GB/s/chip).

BASELINE.json config 1 scaled to saturate one chip: rowwise JLT apply
A·Sᵀ on a dense matrix (ref: sketch/JLT.hpp +
sketch/dense_transform_Elemental_local.hpp). The sketch operator is
generated on the fly from (seed, counter) and fused into the matmul, so
effective bytes = read(A) + write(SA); the reference has no published
numbers (BASELINE.md), so ``vs_baseline`` is the ratio against the
previous round's recorded value when a BENCH_r*.json exists, else 1.0.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np


def run(m: int = 8192, n: int = 8192, s: int = 1024, repeats: int = 5):
    from jax import lax

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import JLT, ROWWISE

    ctx = Context(seed=0)
    jlt = JLT(n, s, ctx)

    rng = np.random.default_rng(1)
    A = jax.device_put(jnp.asarray(
        rng.standard_normal((m, n), dtype=np.float32)))

    # K on-device apply iterations chained by a data dependence (so XLA
    # cannot CSE them), synced by a scalar host readback. Per-iteration
    # time = slope between two K values — cancels dispatch/tunnel
    # round-trip latency, which on this platform `block_until_ready`
    # does not capture.
    def iterate(X, K):
        def body(_, acc):
            SA = jlt.apply(X + acc * 1e-30, ROWWISE)
            return jnp.float32(SA[0, 0])

        return lax.fori_loop(0, K, body, jnp.float32(0.0))

    k1, k2 = 2, 12
    f1 = jax.jit(lambda X: iterate(X, k1))
    f2 = jax.jit(lambda X: iterate(X, k2))
    float(f1(A))  # compile + warm
    float(f2(A))

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f1(A))
        t1 = time.perf_counter()
        float(f2(A))
        t2 = time.perf_counter()
        best = min(best, ((t2 - t1) - (t1 - t0)) / (k2 - k1))

    bytes_moved = 4 * (m * n + m * s)
    return bytes_moved / best / 1e9, best


def _previous_value() -> float | None:
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        mm = re.search(r"BENCH_r(\d+)\.json$", p)
        if not mm:
            continue
        try:
            with open(p) as fh:
                rec = json.load(fh)
            rounds.append((int(mm.group(1)), float(rec["value"])))
        except Exception:
            continue
    return max(rounds)[1] if rounds else None


def main():
    gbps, secs = run()
    prev = _previous_value()
    vs = gbps / prev if prev else 1.0
    print(json.dumps({
        "metric": "jlt_sketch_apply_GBps_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
