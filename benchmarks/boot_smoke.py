"""CI boot gate: zero-recompile fleet boot from a warmup pack.

The r13 contract (docs/performance, "Persistent AOT artifacts & warmup
packs"), proven end to end:

1. Build a 2-bucket warmup pack in this process (a JLT rowwise bucket
   and a CWT columnwise bucket, two capacity classes each) — every
   packed (bucket, capacity) executable serialized, the manifest
   recording the kernel decision and the builder's result digests.
2. Boot a FRESH python process (``skylark_warmup boot-probe``) that
   loads the pack and serves every packed bucket's canonical cohort.
   Assert, from the child's own engine counters:
   - **zero backend compiles** (``compiles == 0``): every executable
     arrived as an AOT artifact load (``aot_loads == entries``), and
     every first request was a cache HIT (``misses == 0``);
   - **bit-equality**: the child's results hash to exactly the
     builder's in-process digests — the deserialized executable is
     the builder's program, bit for bit;
   - the pack loaded cleanly (nothing skipped, nothing failed, the
     kernel decisions restored from the manifest).
3. Boot a second fresh process WITHOUT the pack on the same cohorts
   and assert it did compile (> 0) — proving the zero above is the
   pack's doing, not an accident of the workload.

Prints one JSON record; exits nonzero on any violation (the CI boot
gate). Runs anywhere (JAX_PLATFORMS=cpu); ~4 bucket-capacity compiles
in the builder plus two child boots.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")


def _fail(msg: str) -> None:
    print(f"BOOT SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    import shutil

    from libskylark_tpu.engine import warmup

    pack = tempfile.mkdtemp(prefix="skylark_boot_smoke_")
    # the pack (serialized executables included) is per-run scratch;
    # _fail exits via sys.exit, so atexit-style cleanup must not be
    # conditional on reaching the end of main
    import atexit

    atexit.register(shutil.rmtree, pack, ignore_errors=True)
    specs = [
        warmup.BucketSpec(endpoint="sketch_apply", family="JLT",
                          n=120, m=28, s_dim=32, rowwise=True,
                          capacities=(1, 2)),
        warmup.BucketSpec(endpoint="sketch_apply", family="CWT",
                          n=48, m=6, s_dim=16, rowwise=False,
                          capacities=(2,)),
    ]
    manifest = warmup.build_pack(pack, specs)
    n_entries = len(manifest["entries"])
    if n_entries < 3:
        _fail(f"builder packed {n_entries} entries, expected 3 "
              f"(2 JLT capacities + 1 CWT)")
    missing = [e["digest"] for e in manifest["entries"]
               if e.get("artifact_missing")]
    if missing:
        _fail(f"builder produced no artifact for {missing}")
    if any(not e.get("kernel") for e in manifest["entries"]):
        _fail("manifest entries missing the kernel decision token")

    # fresh children via the one shared launcher (hermetic env scrub
    # included — engine.warmup.spawn_boot_probe)
    try:
        warm = warmup.spawn_boot_probe(pack, load=True)
        cold = warmup.spawn_boot_probe(pack, load=False)
    except RuntimeError as e:
        _fail(str(e))

    eng = warm["engine"]
    wrep = warm.get("warmup") or {}
    if wrep.get("skipped") is not None:
        _fail(f"fresh process skipped the pack: {wrep['skipped']}")
    if wrep.get("failed"):
        _fail(f"{wrep['failed']} pack entries failed to load")
    if wrep.get("loaded") != n_entries:
        _fail(f"loaded {wrep.get('loaded')} of {n_entries} entries")
    if wrep.get("kernel_restored") != n_entries:
        _fail(f"kernel decisions restored for "
              f"{wrep.get('kernel_restored')} of {n_entries} entries "
              f"(manifest-restored selection broke)")
    if eng["compiles"] != 0:
        _fail(f"fresh process performed {eng['compiles']} backend "
              f"compile(s) despite the warmup pack")
    if eng["misses"] != 0:
        _fail(f"fresh process MISSED {eng['misses']} time(s) — packed "
              f"keys did not match the serve path's keys")
    if eng["aot_loads"] != n_entries:
        _fail(f"aot_loads {eng['aot_loads']} != entries {n_entries}")
    if not warm["bit_equal"]:
        _fail(f"pack-booted results diverged from the in-process "
              f"builder's: {warm['mismatches']}")
    if not cold["bit_equal"]:
        _fail("cold-booted results diverged from the in-process "
              "builder's (determinism of the serve path itself broke)")
    if cold["engine"]["compiles"] == 0:
        _fail("cold probe compiled nothing — the zero-compile claim "
              "above proved nothing")

    print(json.dumps({
        "entries": n_entries,
        "warm": {"compiles": eng["compiles"], "misses": eng["misses"],
                 "aot_loads": eng["aot_loads"],
                 "load_seconds": eng["load_seconds"],
                 "bit_equal": warm["bit_equal"],
                 "wall_since_spawn_s": warm.get("wall_since_spawn_s")},
        "cold": {"compiles": cold["engine"]["compiles"],
                 "compile_seconds": cold["engine"]["compile_seconds"],
                 "bit_equal": cold["bit_equal"],
                 "wall_since_spawn_s": cold.get("wall_since_spawn_s")},
        "kernel_restored": wrep.get("kernel_restored"),
        "ok": True,
    }))


if __name__ == "__main__":
    main()
