"""Cache smoke — the CI cache gate's driver (docs/caching).

A 2-replica fleet hot-operand storm asserting the content-addressed
caching tier's contract end to end, fast enough for the per-commit
gate:

- **hit rate**: after a one-pass warmup the storm's repeat requests
  are served from the replicas' digest→result caches with aggregate
  hit-rate > 0.9;
- **one flush per unique request**: across the whole warmup + storm,
  the fleet runs EXACTLY one flush per unique (digest, statics, seed)
  — a duplicate never recomputes, and the same operand bytes under a
  different Context seed never share a flush (the miscoalesce
  regression);
- **front-door single-flight**: a concurrent storm of one fresh
  digest coalesces at the router — every follower fans bit-equal off
  ONE added flush;
- **bit-equality**: every cached result is bit-equal to the uncached
  control (the sequential ``transform.apply`` oracle — stream
  exactness survives the cache);
- **zero recompiles** across the measured storm (the cache serves
  hits without touching the executable cache);
- **residency round-trip over the process transport**: a
  ``register_operand`` broadcast to a process replica rides the SHM
  rings, a ref submit resolves bit-equal, unregister drops the pin,
  and **no /dev/shm transport segments leak** at exit.

Usage: ``python benchmarks/cache_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_STORM = 80
N_UNIQUE = 4
MAX_BATCH = 8
CLASSES = (40, 96)          # two pow2 stream classes (pad 64 / 128)
S_DIM = 16


def _fleet_cache_stats(pool) -> dict:
    from libskylark_tpu.engine import resultcache as rc

    blocks = [pool.get(n).executor.stats().get("cache")
              for n in pool.names()]
    merged = rc.merge_cache_blocks([b for b in blocks if b])
    merged["flushes"] = sum(
        pool.get(n).executor.stats()["flushes"] for n in pool.names())
    return merged


def main() -> int:
    import jax
    import jax.numpy as jnp

    from libskylark_tpu import Context, engine, fleet
    from libskylark_tpu import sketch as sk

    engine.reset()
    violations: list = []
    rng = np.random.default_rng(0)

    # N_UNIQUE unique requests over two bucket classes, each under its
    # own Context seed — unique CONTENT, shared buckets
    uniq = []
    for i in range(N_UNIQUE):
        n = CLASSES[i % len(CLASSES)]
        T = sk.CWT(n, S_DIM, Context(seed=i))
        A = rng.standard_normal((n, 3 + i)).astype(np.float32)
        uniq.append((T, A))
    oracle = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
              for (T, A) in uniq]

    pool = fleet.ReplicaPool(2, max_batch=MAX_BATCH, linger_us=2000,
                             cache=True)
    router = fleet.Router(pool, cache=True)
    rec: dict = {"n_storm": N_STORM, "n_unique": N_UNIQUE}
    try:
        # -- warmup: each unique computes exactly once ----------------
        for (T, A) in uniq:
            router.submit_sketch(T, A).result(timeout=120)
        # the settle callback inserts AFTER the future resolves —
        # barrier on the fleet-wide entry count before the storm
        deadline = time.monotonic() + 30
        while (_fleet_cache_stats(pool)["entries"] < N_UNIQUE
               and time.monotonic() < deadline):
            time.sleep(0.005)
        st0 = _fleet_cache_stats(pool)
        if st0["flushes"] != N_UNIQUE:
            violations.append(
                f"warmup ran {st0['flushes']} flushes for "
                f"{N_UNIQUE} unique requests")
        eng0 = engine.stats()
        compiles0 = (eng0.misses, eng0.recompiles)

        # -- hot storm: every request is a repeat ---------------------
        outs = []
        for i in range(N_STORM):
            T, A = uniq[i % N_UNIQUE]
            outs.append(np.asarray(
                router.submit_sketch(T, A).result(timeout=120)))
        st1 = _fleet_cache_stats(pool)
        eng1 = engine.stats()
        rec["hit_rate"] = st1["hit_rate"]
        rec["hits"] = st1["hits"]
        rec["misses"] = st1["misses"]
        rec["bytes_saved"] = st1["bytes_saved"]
        rec["flushes_total"] = st1["flushes"]
        rec["recompiles_storm"] = (
            eng1.misses - compiles0[0], eng1.recompiles - compiles0[1])
        if st1["hit_rate"] is None or st1["hit_rate"] <= 0.9:
            violations.append(
                f"storm hit-rate {st1['hit_rate']} <= 0.9")
        if st1["flushes"] != N_UNIQUE:
            violations.append(
                f"{st1['flushes']} flushes for {N_UNIQUE} unique "
                "requests — a duplicate recomputed or a unique "
                "coalesced")
        if rec["recompiles_storm"] != (0, 0):
            violations.append(
                f"storm compiled: misses/recompiles "
                f"{rec['recompiles_storm']}")
        for i, out in enumerate(outs):
            if not np.array_equal(out, oracle[i % N_UNIQUE]):
                violations.append(
                    f"storm request {i} diverged from the uncached "
                    "oracle")
                break

        # -- miscoalesce regression: same bytes, different seed -------
        T0, A0 = uniq[0]
        T_alt = sk.CWT(CLASSES[0], S_DIM, Context(seed=77))
        alt = np.asarray(
            router.submit_sketch(T_alt, A0).result(timeout=120))
        if np.array_equal(alt, oracle[0]):
            violations.append(
                "different-seed request returned the cached seed-0 "
                "result (miscoalesce)")
        if not np.array_equal(
                alt, np.asarray(T_alt.apply(jnp.asarray(A0),
                                            sk.COLUMNWISE))):
            violations.append(
                "different-seed request diverged from its own oracle")

        # -- front-door single-flight: one fresh digest, stormed ------
        T_sf = sk.CWT(CLASSES[0], S_DIM, Context(seed=88))
        A_sf = rng.standard_normal((CLASSES[0], 5)).astype(np.float32)
        flushes_before = _fleet_cache_stats(pool)["flushes"]
        futs = [router.submit_sketch(T_sf, A_sf) for _ in range(16)]
        sf_outs = [np.asarray(f.result(timeout=120)) for f in futs]
        rs = router.stats()
        sf_flushes = _fleet_cache_stats(pool)["flushes"] - flushes_before
        rec["single_flight"] = {
            "coalesced": rs["coalesced"],
            "routed_total": rs["routed"],
            "flushes_added": sf_flushes,
        }
        want = np.asarray(T_sf.apply(jnp.asarray(A_sf), sk.COLUMNWISE))
        if any(not np.array_equal(o, want) for o in sf_outs):
            violations.append("single-flight fan diverged")
        if sf_flushes != 1:
            violations.append(
                f"single-flight storm added {sf_flushes} flushes, "
                "expected exactly 1")
    finally:
        router.close()
        pool.shutdown()

    # -- residency over the process transport + /dev/shm hygiene ------
    pool2 = fleet.ReplicaPool(1, backend="process", max_batch=MAX_BATCH,
                              cache=True)
    try:
        router2 = fleet.Router(pool2, cache=True)
        try:
            T0, A0 = uniq[0]
            ref = router2.register_operand(A0)
            via = np.asarray(router2.submit_sketch(T0, ref)
                             .result(timeout=180))
            if not np.array_equal(via, oracle[0]):
                violations.append(
                    "process-replica ref submit diverged from oracle")
            held = router2.unregister_operand(ref)
            if held != 1:
                violations.append(
                    f"unregister dropped {held} pins, expected 1")
            rec["residency_process_leg"] = {
                "ref": str(ref)[:12], "unregistered_from": held}
        finally:
            router2.close()
    finally:
        pool2.shutdown()
    leaked = fleet.shm_entries()
    if leaked:
        violations.append(f"leaked /dev/shm entries: {leaked}")
    rec["shm_leaks"] = len(leaked)

    rec["violations"] = violations
    rec["ok"] = not violations
    print(json.dumps(rec), flush=True)
    if violations:
        for v in violations:
            print(f"CACHE GATE VIOLATION: {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
