"""Deterministic chaos battery — the CI chaos gate's driver.

Runs a fixed serve storm under a seeded ``SKYLARK_FAULT_PLAN`` and
asserts the resilience subsystem's contract end to end:

- **zero orphaned futures**: every submitted request resolves (result
  or exception) — a failure path that strands a future deadlocks a
  real client;
- **poison isolation**: the single tagged poison request in a *full*
  cohort fails alone with the injected error class; every cohort-mate
  re-coalesces and succeeds **bit-equal to the fault-free run**
  (transform.apply is the clean oracle — the CWT serve path is
  bit-exact against it);
- **bounded convergence**: bisection pins the poison in
  ≤ log2(max_batch) retry levels (the executor's
  ``isolation_depth_peak`` counter);
- **determinism**: two runs under the same plan seed produce the
  identical injected-fault sequence (``faults.fired()``) and identical
  surviving-request bits;
- **zero leaked executables**: the engine's jit-leak counter
  (``recompiles``) stays 0 and every miss is accounted
  (``hits + misses == executions``) — chaos must not thrash the
  executable cache;
- **clean drain**: ``drain()`` after the storm reaches quiescence;
- **deterministic router failover** (the fleet leg): a fixed-seed
  ``fleet.route`` fault storm through a 3-replica
  :class:`~libskylark_tpu.fleet.Router` — every injected route fault
  fails over to the next ring candidate, every request still resolves
  bit-equal to the fault-free oracle, the failover counter equals the
  fired-fault count, and two same-seed runs replay the identical
  fired sequence. Route checks run on the submitting thread, so the
  hit order — unlike flush-side hits under concurrent workers — is
  deterministic by construction;
- **hedged-straggler rescue** (the hedge leg): a tag-pinned
  ``stall_s`` fault makes one request's primary flush a straggler; a
  hedging router must mirror it after its fixed delay, take the
  mirror's result (``hedge_wins``), let the stalled loser complete
  (verify mode), and prove the determinism guard: both executions
  bit-equal, zero mismatches, zero orphans, and the identical fired
  sequence across two same-seed runs;
- **survivable sessions** (the session leg, docs/sessions): a CWT
  session streamed through a 2-replica router with the owner
  preempted mid-stream AND a seeded ``session.append`` fault — the
  drain handoff resumes on the peer, the same-seq retry absorbs the
  fault (idempotent replay), finalize is bit-equal to the one-shot
  sketch, zero client-visible failures, and two same-seed runs replay
  the identical fired sequence;
- **fault-tolerant distributed sketching** (the dist leg,
  docs/distributed): a fixed-seed ``dist.shard`` crash/retry storm
  through a 2-replica :class:`~libskylark_tpu.dist.
  DistSketchCoordinator` (``max_inflight=1`` serializes dispatch so
  the hit order is deterministic by construction) — every fired fault
  is absorbed by a reassigned re-execution, the full-coverage merge
  is **bit-equal to the one-shot** ``sketch_local`` reference, two
  same-seed runs replay the identical fired sequence AND identical
  bits; a second, budget-exhausting plan forces abandonment and the
  leg asserts the degraded path's exact coverage arithmetic, missing
  row ranges, and the ``min_coverage`` raise;
- **preemptible training jobs** (the train leg, docs/training): a
  sliced Block-ADMM KRR job through a 2-replica router with a seeded
  ``train.slice`` fault fired BEFORE the slice's journaled append —
  the manager's retry budget re-runs the exact same slice, the job
  completes **bit-equal to the fault-free engine run** with zero
  client-visible failures, the manager's retry counter equals the
  fired-fault count, and two same-seed runs replay the identical
  fired sequence.

Usage: ``python benchmarks/chaos_battery.py --gate`` (script/ci wires
``JAX_PLATFORMS=cpu`` and the canned ``SKYLARK_FAULT_PLAN``). Prints
one JSON record; exits nonzero on any violation. The storm uses forced
flushes and an effectively-infinite linger, so cohort composition —
and therefore the fault-hit sequence — is deterministic by
construction, which is what makes the replay comparison meaningful.
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Chaos runs are hardware-independent; default to CPU unless the
# caller pinned a platform (the conftest discipline).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_REQUESTS = 48
MAX_BATCH = 8
POISON_INDEX = 11       # second cohort, middle lane — a FULL cohort
S_DIM = 16
N_FEAT = 40

# The canned plan: a request-pinned poison plus a one-shot transient
# flush fault landing on a known full-cohort attempt — bisection must
# absorb it with zero client-visible failures (both halves re-execute
# clean), in contrast to the poison, which must fail exactly one
# future. The battery asserts the transient actually fired (an inert
# plan is a gate bug, not a pass).
DEFAULT_PLAN = {
    "seed": 7,
    "faults": [
        {"site": "serve.flush", "error": "SketchError", "tag": "poison"},
        {"site": "serve.flush", "error": "IOError_", "on_hit": 5},
    ],
}


def _requests():
    from libskylark_tpu import Context
    from libskylark_tpu import sketch as sk

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    T = sk.CWT(N_FEAT, S_DIM, ctx)
    ops = [rng.standard_normal((N_FEAT, 3 + i % 4)).astype(np.float32)
           for i in range(N_REQUESTS)]
    return T, ops


def _clean_refs(T, ops):
    import jax.numpy as jnp

    from libskylark_tpu import sketch as sk

    return [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for A in ops]


def _storm(T, ops):
    """One deterministic storm: submit in cohort-sized groups (forced
    flush each), poison one request, drain. Returns outcomes + logs."""
    from libskylark_tpu import engine
    from libskylark_tpu.resilience import faults

    ex = engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                   linger_us=10_000_000)
    futs = []
    for i, A in enumerate(ops):
        if i == POISON_INDEX:
            with faults.tag("poison"):
                futs.append(ex.submit_sketch(T, A))
        else:
            futs.append(ex.submit_sketch(T, A))
        if (i + 1) % MAX_BATCH == 0:
            ex.flush()
    ex.flush()
    drained = ex.drain(timeout=60.0)
    outcomes = []
    for f in futs:
        if not f.done():
            outcomes.append(("ORPHANED", None))
        elif f.exception() is not None:
            outcomes.append(("ERROR", type(f.exception()).__name__))
        else:
            outcomes.append(("OK", np.asarray(f.result())))
    return outcomes, faults.fired(), ex.stats(), drained


# The fleet leg's canned plan: fleet.route-only, because route checks
# happen on the (single) submitting thread — their hit order is
# deterministic, which is what makes the replay comparison exact. A
# serve.flush spec here would race across the replicas' worker threads.
FLEET_PLAN = {
    "seed": 13,
    "faults": [
        {"site": "fleet.route", "error": "IOError_", "every": 5},
    ],
}
FLEET_REPLICAS = 3


def _fleet_storm(T, ops):
    """One deterministic routed storm over a 3-replica fleet: submit
    in cohort groups (pool-flushed each), drain. Returns outcomes,
    the fired log, and the router's counters."""
    from libskylark_tpu import fleet
    from libskylark_tpu.resilience import faults

    pool = fleet.ReplicaPool(FLEET_REPLICAS, max_batch=MAX_BATCH,
                             linger_us=10_000_000)
    router = fleet.Router(pool)
    futs = []
    for i, A in enumerate(ops):
        futs.append(router.submit_sketch(T, A))
        if (i + 1) % MAX_BATCH == 0:
            pool.flush()
    pool.flush()
    outcomes = []
    for f in futs:
        if not f.done():
            outcomes.append(("ORPHANED", None))
        elif f.exception() is not None:
            outcomes.append(("ERROR", type(f.exception()).__name__))
        else:
            outcomes.append(("OK", np.asarray(f.result())))
    stats = router.stats()
    fired = faults.fired()
    router.close()
    pool.shutdown()
    return outcomes, fired, stats


def _fleet_leg(T, ops, refs, violations):
    from libskylark_tpu.resilience import faults

    with faults.fault_plan(dict(FLEET_PLAN)):
        out1, fired1, stats1 = _fleet_storm(T, ops)
    with faults.fault_plan(dict(FLEET_PLAN)):
        out2, fired2, stats2 = _fleet_storm(T, ops)

    orphans = sum(1 for s, _ in out1 + out2 if s == "ORPHANED")
    if orphans:
        violations.append(f"fleet leg: {orphans} orphaned future(s)")
    for run, out in (("run1", out1), ("run2", out2)):
        for i, (status, val) in enumerate(out):
            if status != "OK":
                violations.append(
                    f"fleet leg {run}: request {i} got {status}/{val} "
                    "— a route fault leaked to a client")
                break
            if not np.array_equal(val, refs[i]):
                violations.append(
                    f"fleet leg {run}: request {i} not bit-equal to "
                    "the fault-free oracle")
                break
    if fired1 != fired2:
        violations.append(
            f"fleet leg: fired sequences differ across same-seed "
            f"runs: {fired1} vs {fired2}")
    if not fired1:
        violations.append("fleet leg: plan injected nothing — inert")
    if any(site != "fleet.route" for site, _, _ in fired1):
        violations.append("fleet leg: unexpected site in fired log")
    for run, st in (("run1", stats1), ("run2", stats2)):
        if st["failover"] != len(fired1):
            violations.append(
                f"fleet leg {run}: failover count {st['failover']} != "
                f"fired route faults {len(fired1)}")
        if st["routed"] != len(ops):
            violations.append(
                f"fleet leg {run}: routed {st['routed']} != "
                f"{len(ops)} submitted")
    return {
        "replicas": FLEET_REPLICAS,
        "fired": [list(f) for f in fired1],
        "failover": stats1["failover"],
        "affinity_hit_rate": stats1["affinity_hit_rate"],
        "deterministic": fired1 == fired2,
    }


# The hedge leg's canned plan: a tag-pinned STALL on the primary's
# flush (a straggler, not an error — stall_s sleeps and proceeds).
# The router's watchdog must mirror the request to the second ring-
# preference replica after its fixed hedge delay and take the mirror's
# result; verify mode lets the stalled loser complete and compares
# both bitwise — the determinism guard (the endpoints are pure, so the
# two executions must agree to the bit).
HEDGE_PLAN = {
    "seed": 17,
    "faults": [
        {"site": "serve.flush", "stall_s": 0.35, "tag": "hedge-stall"},
    ],
}
HEDGE_DELAY_MS = 50


def _hedge_storm(T, ops):
    import time as _time

    from concurrent.futures import wait as cf_wait

    from libskylark_tpu import fleet
    from libskylark_tpu.resilience import faults

    pool = fleet.ReplicaPool(2, max_batch=MAX_BATCH, linger_us=1000)
    router = fleet.Router(pool, hedge=True,
                          hedge_delay_ms=HEDGE_DELAY_MS,
                          hedge_verify=True)
    # warm BOTH replicas for the class: the mirror must answer from a
    # warm cache so the race is about queueing, not compiles
    for name in pool.names():
        pool.get(name).submit("sketch_apply", transform=T, A=ops[0],
                              dimension=None).result(timeout=120)
    # ONE tagged request: the leg isolates the straggler-rescue
    # mechanism (storm semantics are the fleet leg's job) — on a
    # loaded 1-core host a full storm would hedge on ordinary backlog
    # too, making "exactly one hedge" unassertable
    with faults.tag("hedge-stall"):
        futs = [router.submit_sketch(T, ops[0])]
    cf_wait(futs, timeout=120)
    # both-attempts-complete: wait until every executor quiesces (the
    # stalled loser's flush finishes and resolves its future)
    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline and any(
            pool.get(n).queue_depth() for n in pool.names()):
        _time.sleep(0.02)
    inflight = sum(pool.get(n).queue_depth() for n in pool.names())
    outcomes = []
    for f in futs:
        if not f.done():
            outcomes.append(("ORPHANED", None))
        elif f.exception() is not None:
            outcomes.append(("ERROR", type(f.exception()).__name__))
        else:
            outcomes.append(("OK", np.asarray(f.result())))
    stats = router.stats()
    fired = faults.fired()
    router.close()
    pool.shutdown()
    return outcomes, fired, stats, inflight


def _hedge_leg(T, ops, refs, violations):
    from libskylark_tpu.resilience import faults

    runs = []
    for _ in range(2):
        with faults.fault_plan(dict(HEDGE_PLAN)):
            runs.append(_hedge_storm(T, ops))
    (out1, fired1, stats1, in1), (out2, fired2, stats2, in2) = runs

    orphans = sum(1 for s, _ in out1 + out2 if s == "ORPHANED")
    if orphans or in1 or in2:
        violations.append(
            f"hedge leg: {orphans} orphaned future(s), "
            f"{in1 + in2} stuck in-flight")
    for run, out in (("run1", out1), ("run2", out2)):
        status, val = out[0]
        if status != "OK":
            violations.append(
                f"hedge leg {run}: request got {status}/{val}")
        elif not np.array_equal(val, refs[0]):
            violations.append(
                f"hedge leg {run}: result not bit-equal to the "
                "unhedged oracle")
    for run, st in (("run1", stats1), ("run2", stats2)):
        if st["hedged"] != 1:
            violations.append(
                f"hedge leg {run}: hedged {st['hedged']} != 1 — the "
                "injected stall did not trigger exactly one hedge")
        if st["hedge_wins"] != 1:
            violations.append(
                f"hedge leg {run}: the mirror did not win against a "
                f"{HEDGE_PLAN['faults'][0]['stall_s']}s straggler")
        if st["hedge_mismatches"]:
            violations.append(
                f"hedge leg {run}: {st['hedge_mismatches']} hedge "
                "result mismatch(es) — an endpoint is no longer "
                "deterministic")
    if fired1 != fired2:
        violations.append(
            f"hedge leg: fired sequences differ across same-seed "
            f"runs: {fired1} vs {fired2}")
    if not fired1 or any(e[2] != "stall" for e in fired1):
        violations.append(
            f"hedge leg: expected only stall firings, got {fired1}")
    return {
        "fired": [list(f) for f in fired1],
        "hedged": stats1["hedged"],
        "hedge_wins": stats1["hedge_wins"],
        "hedge_mismatches": stats1["hedge_mismatches"],
        "deterministic": fired1 == fired2 and [s for s, _ in out1]
        == [s for s, _ in out2],
    }


def _session_run(A, ref, plan_doc):
    """One fixed-seed stateful-session episode (docs/sessions): a CWT
    session streamed through a 2-replica router, the owner preempted
    mid-stream (drain handoff), an injected ``session.append`` fault
    absorbed by a same-seq retry (idempotent replay), finalize
    compared bit-equal to the one-shot sketch."""
    import shutil
    import tempfile

    from libskylark_tpu import fleet
    from libskylark_tpu.resilience import faults

    prev_dir = os.environ.get("SKYLARK_SESSION_DIR")
    scratch = tempfile.mkdtemp(prefix="skylark_chaos_sessions_")
    os.environ["SKYLARK_SESSION_DIR"] = scratch
    pool = fleet.ReplicaPool(2, max_batch=4)
    router = fleet.Router(pool)
    client_failures = 0
    retries = 0
    try:
        with faults.fault_plan(plan_doc) as plan:
            sid = router.open_sketch_session(
                "cwt", n=64, s_dim=16, d=8, seed=21, owner="r0")
            for i in range(4):
                if i == 2:
                    # SIGTERM-semantics preemption of the session
                    # owner mid-stream: checkpoint + peer resume
                    pool.preempt_replica(router.session_owner(sid))
                for attempt in range(3):
                    try:
                        router.session_append(
                            sid, A[i * 16:(i + 1) * 16],
                            seq=i + 1).result(timeout=30.0)
                        break
                    except Exception:  # noqa: BLE001 — retry same seq
                        retries += 1
                else:
                    client_failures += 1
            out = router.session_finalize(sid).result(timeout=30.0)
            fired = list(plan.fired)
        stats = router.stats()
        return {
            "bits_equal": bool(np.array_equal(out["SX"], ref)),
            "fired": fired,
            "retries": retries,
            "client_visible_failures": client_failures,
            "session_handoffs": stats["session_handoffs"],
        }
    finally:
        router.close()
        pool.shutdown()
        if prev_dir is None:
            os.environ.pop("SKYLARK_SESSION_DIR", None)
        else:
            os.environ["SKYLARK_SESSION_DIR"] = prev_dir
        shutil.rmtree(scratch, ignore_errors=True)


def _session_leg(violations):
    """Sessions under chaos, twice with the same seed: the injected
    fault sequence and the finalize bits must replay identically, with
    zero client-visible failures and at least one real handoff."""
    import jax.numpy as jnp

    from libskylark_tpu import Context
    from libskylark_tpu import sketch as sk

    A = np.random.default_rng(21).standard_normal(
        (64, 8)).astype(np.float32)
    ref = np.asarray(sk.CWT(64, 16, Context(seed=21)).apply(
        jnp.asarray(A), sk.COLUMNWISE))
    plan_doc = {"seed": 7, "faults": [
        {"site": "session.append", "error": "IOError_", "on_hit": 3}]}
    rec1 = _session_run(A, ref, plan_doc)
    rec2 = _session_run(A, ref, plan_doc)
    for run, rec in (("run1", rec1), ("run2", rec2)):
        if not rec["bits_equal"]:
            violations.append(
                f"session leg {run}: finalize not bit-equal to the "
                "one-shot sketch through drain + injected fault")
        if rec["client_visible_failures"]:
            violations.append(
                f"session leg {run}: "
                f"{rec['client_visible_failures']} client-visible "
                "failure(s)")
        if rec["session_handoffs"] < 1:
            violations.append(
                f"session leg {run}: owner preemption produced no "
                "session handoff")
    if not rec1["fired"]:
        violations.append("session leg: plan injected nothing — inert")
    if rec1["fired"] != rec2["fired"]:
        violations.append(
            f"session leg: fired sequences differ across same-seed "
            f"runs: {rec1['fired']} vs {rec2['fired']}")
    return {
        "fired": [list(f) for f in rec1["fired"]],
        "retries": rec1["retries"],
        "session_handoffs": rec1["session_handoffs"],
        "client_visible_failures": rec1["client_visible_failures"],
        "deterministic": rec1["fired"] == rec2["fired"],
    }


def _dist_run(A, plan_doc, *, retries, min_coverage):
    """One fixed-seed distributed-sketch storm (docs/distributed): a
    7-shard CWT plan over a 2-replica fleet, dispatch serialized
    (``max_inflight=1``) so the ``dist.shard`` hit order — and
    therefore the fired sequence — is deterministic by construction."""
    from libskylark_tpu import dist, fleet
    from libskylark_tpu.base import errors as sk_errors
    from libskylark_tpu.resilience import faults

    plan = dist.ShardPlan(kind="cwt", n=64, s_dim=S_DIM, d=8, seed=23,
                          shard_rows=10)
    src = dist.ArraySource(A)
    pool = fleet.ReplicaPool(2, max_batch=4)
    try:
        co = dist.DistSketchCoordinator(pool, retries=retries,
                                        max_inflight=1)
        with faults.fault_plan(plan_doc) as p:
            gate_raised = False
            result = None
            try:
                result = co.sketch(plan, src,
                                   min_coverage=min_coverage)
            except sk_errors.SketchCoverageError:
                gate_raised = True
            fired = list(p.fired)
        return {"result": result, "fired": fired,
                "gate_raised": gate_raised, "stats": co.stats(),
                "plan": plan, "source": src}
    finally:
        pool.shutdown()


def _dist_leg(violations):
    """Distributed sketching under chaos, twice per plan seed."""
    from libskylark_tpu import dist

    A = np.random.default_rng(23).standard_normal(
        (64, 8)).astype(np.float32)

    # -- retry storm: every third shard-task execution fails ------------
    storm_plan = {"seed": 7, "faults": [
        {"site": "dist.shard", "error": "IOError_", "every": 3}]}
    rec1 = _dist_run(A, storm_plan, retries=3, min_coverage=1.0)
    rec2 = _dist_run(A, storm_plan, retries=3, min_coverage=1.0)
    ref = dist.sketch_local(rec1["plan"], rec1["source"])
    for run, rec in (("run1", rec1), ("run2", rec2)):
        r = rec["result"]
        if r is None:
            violations.append(
                f"dist leg {run}: storm raised instead of absorbing "
                "the injected shard faults")
            continue
        if r.coverage != 1.0 or rec["stats"]["abandoned"]:
            violations.append(
                f"dist leg {run}: coverage {r.coverage} with "
                f"{rec['stats']['abandoned']} abandoned — the retry "
                "budget should have absorbed every fault")
        if not np.array_equal(r.SX, ref.SX):
            violations.append(
                f"dist leg {run}: merged sketch not bit-equal to the "
                "one-shot sketch_local reference")
        if rec["stats"]["retried"] < 1:
            violations.append(
                f"dist leg {run}: plan fired but nothing retried")
    if not rec1["fired"]:
        violations.append("dist leg: plan injected nothing — inert")
    if rec1["fired"] != rec2["fired"]:
        violations.append(
            f"dist leg: fired sequences differ across same-seed runs: "
            f"{rec1['fired']} vs {rec2['fired']}")
    if (rec1["result"] is not None and rec2["result"] is not None
            and not np.array_equal(rec1["result"].SX,
                                   rec2["result"].SX)):
        violations.append(
            "dist leg: merged bits differ across same-seed runs")

    # -- forced abandonment: everything after hit 2 fails ---------------
    kill_plan = {"seed": 7, "faults": [
        {"site": "dist.shard", "error": "IOError_", "after": 2}]}
    gated = _dist_run(A, kill_plan, retries=1, min_coverage=1.0)
    if not gated["gate_raised"]:
        violations.append(
            "dist leg: degraded merge below min_coverage=1.0 did not "
            "raise SketchCoverageError")
    deg = _dist_run(A, kill_plan, retries=1, min_coverage=0.25)
    r = deg["result"]
    if r is None:
        violations.append(
            "dist leg: degraded run raised despite min_coverage=0.25")
    else:
        # shards 0,1 complete (hits 1,2); shards 2..6 fail every
        # attempt: coverage = 20/64, missing = rows [20, 64)
        if (r.coverage != 20 / 64 or r.missing != ((20, 64),)
                or r.rows_merged != 20):
            violations.append(
                f"dist leg: degraded accounting wrong — coverage "
                f"{r.coverage} missing {r.missing} rows "
                f"{r.rows_merged}, expected 20/64, ((20, 64),), 20")
        if deg["stats"]["abandoned"] != 5:
            violations.append(
                f"dist leg: {deg['stats']['abandoned']} abandoned "
                "shards, expected 5")
    return {
        "fired": [list(f) for f in rec1["fired"]],
        "retried": rec1["stats"]["retried"],
        "reassigned": rec1["stats"]["reassigned"],
        "degraded_coverage": (None if r is None else r.coverage),
        "degraded_missing": (None if r is None else list(r.missing)),
        "deterministic": rec1["fired"] == rec2["fired"],
    }


def _train_run(ops, plan_doc):
    """One fixed-seed training-job episode (docs/training): a sliced
    Block-ADMM KRR job through a 2-replica router with a seeded
    ``train.slice`` fault — the fault fires BEFORE the slice's
    journaled append, the manager's retry budget re-runs the exact
    same slice, and the job completes. A single job means a single
    flusher drains its slices sequentially, so the hit order — and
    therefore the fired sequence — is deterministic by construction."""
    import shutil
    import tempfile

    from libskylark_tpu import fleet
    from libskylark_tpu.resilience import faults
    from libskylark_tpu.train import TrainJobSpec

    prev_dir = os.environ.get("SKYLARK_SESSION_DIR")
    scratch = tempfile.mkdtemp(prefix="skylark_chaos_train_")
    os.environ["SKYLARK_SESSION_DIR"] = scratch
    pool = fleet.ReplicaPool(2, max_batch=4)
    router = fleet.Router(pool)
    try:
        with faults.fault_plan(plan_doc) as plan:
            fut = router.submit_train_job(
                TrainJobSpec(solver="admm_krr", budget_iters=200,
                             slice_iters=2,
                             hyper={"num_features": 16,
                                    "num_partitions": 2, "lam": 1e-2,
                                    "seed": 3, "tol": 1e-3}).to_dict(),
                operands=ops, session_id="train-chaos")
            out, err = None, None
            try:
                out = fut.result(timeout=120.0)
            except Exception as e:  # noqa: BLE001 — leg accounting
                err = repr(e)
            fired = list(plan.fired)
        retries = sum((r.stats().get("train") or {}).get("retries", 0)
                      for r in pool.replicas())
        return {"out": out, "error": err, "fired": fired,
                "retries": retries}
    finally:
        router.close()
        pool.shutdown()
        if prev_dir is None:
            os.environ.pop("SKYLARK_SESSION_DIR", None)
        else:
            os.environ["SKYLARK_SESSION_DIR"] = prev_dir
        shutil.rmtree(scratch, ignore_errors=True)


def _train_leg(violations):
    """Training jobs under chaos, twice with the same seed: the
    injected slice fault must be absorbed by the retry budget (zero
    client-visible failures), the trained coefficients must be
    bit-equal to the uninterrupted no-chaos engine run, and two
    same-seed runs must replay the identical fired sequence."""
    from libskylark_tpu.train import make_engine

    rng = np.random.default_rng(13)
    X = rng.standard_normal((48, 6))
    ops = {"X": X, "Y": (X[:, :1] > 0).astype(np.float64) * 2 - 1}
    hyper = {"num_features": 16, "num_partitions": 2, "lam": 1e-2,
             "seed": 3, "tol": 1e-3}
    eng = make_engine("admm_krr", hyper, ops)
    st, it = eng.init(), 0
    while it < 200:
        st = eng.step(st, 2)
        it += 2
        if eng.info(st)["converged"]:
            break
    ref = eng.result(st)

    plan_doc = {"seed": 7, "faults": [
        {"site": "train.slice", "error": "IOError_", "on_hit": 2}]}
    rec1 = _train_run(ops, plan_doc)
    rec2 = _train_run(ops, plan_doc)
    for run, rec in (("run1", rec1), ("run2", rec2)):
        if rec["error"] is not None:
            violations.append(
                f"train leg {run}: job failed instead of absorbing "
                f"the injected slice fault: {rec['error']}")
            continue
        out = rec["out"]
        if not out.get("converged"):
            violations.append(f"train leg {run}: job did not converge")
        if not np.array_equal(out["coef"], ref["coef"]):
            violations.append(
                f"train leg {run}: coefficients not bit-equal to the "
                "fault-free engine run")
        if rec["retries"] != len(rec["fired"]):
            violations.append(
                f"train leg {run}: {rec['retries']} manager retries "
                f"for {len(rec['fired'])} fired fault(s) — the retry "
                "budget and the plan disagree")
    if not rec1["fired"]:
        violations.append("train leg: plan injected nothing — inert")
    if any(site != "train.slice" for site, _, _ in rec1["fired"]):
        violations.append("train leg: unexpected site in fired log")
    if rec1["fired"] != rec2["fired"]:
        violations.append(
            f"train leg: fired sequences differ across same-seed "
            f"runs: {rec1['fired']} vs {rec2['fired']}")
    return {
        "fired": [list(f) for f in rec1["fired"]],
        "retries": rec1["retries"],
        "iterations": (None if rec1["out"] is None
                       else rec1["out"]["iterations"]),
        "deterministic": rec1["fired"] == rec2["fired"],
    }


def main() -> int:
    from libskylark_tpu import engine
    from libskylark_tpu.base import errors  # noqa: F401 — class names
    from libskylark_tpu.resilience import faults

    env = os.environ.get("SKYLARK_FAULT_PLAN")

    def make_plan():
        # fresh plan per run (counters/RNG at zero) — FaultPlan.parse
        # owns the inline-JSON-or-path env convention
        return (faults.FaultPlan.parse(env) if env
                else faults.FaultPlan(DEFAULT_PLAN))

    T, ops = _requests()
    refs = _clean_refs(T, ops)

    engine.reset()
    violations = []
    plan1 = make_plan()
    with faults.fault_plan(plan1):
        out1, fired1, stats1, drained1 = _storm(T, ops)
    with faults.fault_plan(make_plan()):
        out2, fired2, stats2, drained2 = _storm(T, ops)

    # -- zero orphaned futures ------------------------------------------
    orphans = sum(1 for s, _ in out1 + out2 if s == "ORPHANED")
    if orphans:
        violations.append(f"{orphans} orphaned future(s)")
    if not (drained1 and drained2):
        violations.append("drain did not reach quiescence")

    # -- poison isolation + bit-equality of survivors -------------------
    for run, out in (("run1", out1), ("run2", out2)):
        for i, (status, val) in enumerate(out):
            if i == POISON_INDEX:
                if status != "ERROR" or val != "SketchError":
                    violations.append(
                        f"{run}: poison request got {status}/{val}, "
                        f"expected the injected SketchError")
            elif status != "OK":
                violations.append(
                    f"{run}: non-poison request {i} got {status}/{val}")
            elif not np.array_equal(val, refs[i]):
                violations.append(
                    f"{run}: request {i} not bit-equal to fault-free run")

    # -- determinism: identical fault sequence + identical bits ---------
    if fired1 != fired2:
        violations.append(
            f"fired-fault sequences differ across same-seed runs: "
            f"{fired1} vs {fired2}")
    for i, ((s1, v1), (s2, v2)) in enumerate(zip(out1, out2)):
        if s1 != s2 or (s1 == "OK" and not np.array_equal(v1, v2)):
            violations.append(f"request {i} outcome differs across runs")
    if not fired1:
        violations.append("plan injected nothing — the battery is inert")
    elif len({e[2] for e in fired1}) < 2 and env is None:
        violations.append(
            "canned plan fired only one error class — the transient-"
            "absorption leg went inert (retune the on_hit)")

    # -- bounded convergence --------------------------------------------
    depth_bound = int(math.ceil(math.log2(MAX_BATCH)))
    for run, st in (("run1", stats1), ("run2", stats2)):
        if st["isolation_depth_peak"] > depth_bound:
            violations.append(
                f"{run}: isolation depth {st['isolation_depth_peak']} > "
                f"log2(max_batch) = {depth_bound}")

    # -- fleet leg: deterministic router failover -----------------------
    fleet_rec = _fleet_leg(T, ops, refs, violations)

    # -- hedge leg: injected stall -> mirrored request ------------------
    hedge_rec = _hedge_leg(T, ops, refs, violations)

    # -- session leg: drain handoff + injected append fault -------------
    session_rec = _session_leg(violations)

    # -- dist leg: shard-crash storm + degraded-merge arithmetic --------
    dist_rec = _dist_leg(violations)

    # -- train leg: injected slice fault -> retry-budget replay ---------
    train_rec = _train_leg(violations)

    # -- lock-order witness (instrumented-lock mode) --------------------
    # With SKYLARK_LOCK_WITNESS=1 (the CI chaos gate sets it) every
    # lock the storm touched — executor state/stats/pub, engine cache,
    # health hub, fault plan, router/pool/ring — was constructed
    # instrumented, and the recorded acquisition-order graph must be
    # acyclic: the runtime half of the lock-discipline story, validated
    # against `script/lint --graph`'s static half on the same battery.
    from libskylark_tpu.base import locks as _locks

    witness_rec = None
    if _locks.witness_enabled():
        witness_rec = _locks.witness_report()
        if not witness_rec["acquisitions"]:
            violations.append(
                "lock witness enabled but recorded nothing — the "
                "instrumented-lock leg went inert")
        for v in witness_rec["violations"]:
            violations.append(
                f"lock-order cycle closed at runtime: "
                f"{v['edge'][0]} -> {v['edge'][1]} "
                f"(held {v['held']}, thread {v['thread']})")

    # -- zero leaked executables (the jit-leak counter) -----------------
    est = engine.stats()
    if est.recompiles:
        violations.append(f"{est.recompiles} executable recompile(s) "
                          "under chaos — cache thrash")
    if est.hits + est.misses != est.executions:
        violations.append(
            f"engine counters unbalanced: hits {est.hits} + misses "
            f"{est.misses} != executions {est.executions}")

    rec = {
        "metric": "chaos_battery",
        "plan_seed": plan1.seed,
        "n_requests": N_REQUESTS,
        "max_batch": MAX_BATCH,
        "faults_fired": len(fired1),
        "fired": [list(f) for f in fired1],
        "poisoned": stats1["poisoned"],
        "isolation_retries": stats1["isolation_retries"],
        "isolation_depth_peak": stats1["isolation_depth_peak"],
        "depth_bound": depth_bound,
        "engine_recompiles": est.recompiles,
        "deterministic": fired1 == fired2,
        "fleet": fleet_rec,
        "hedge": hedge_rec,
        "sessions": session_rec,
        "dist": dist_rec,
        "train": train_rec,
        "lock_witness": witness_rec,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("chaos battery FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
