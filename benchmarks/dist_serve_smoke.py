"""Dist-serve smoke — the CI pipelined-serve chaos gate
(docs/distributed).

Proves the serve-endpoint contract (``Router.submit_dist_sketch``)
over REAL process replicas under a deterministic kill, the tier the
in-process chaos battery cannot reach:

- a **3-process-replica fleet** where ONE child (``r0``) boots with a
  seeded ``SKYLARK_FAULT_PLAN`` carrying a ``crash`` spec at the
  ``dist.shard`` site — a hard ``os._exit(137)`` inside its second
  shard task, the deterministic mid-storm ``kill -9``;
- the client **future resolves normally**: zero client-visible
  failures, coverage **1.0** after reassignment, merged sketch
  **bit-equal** to the one-shot ``sketch_local`` reference (the
  incremental merge tree is associativity-exact, not approximately
  equal), and the pool reaps the victim;
- the run repeats with the same seeds and the dispatch/retry/
  reassignment counts must be **identical** — ``pipeline=1``
  serializes shard dispatch, so the crash point and every recovery
  decision are replayable, not merely survivable;
- **zero engine compiles** in the measured window (shard tasks never
  touch the parent's executable cache) and **no ``/dev/shm`` leaks**
  once the fleets are down (shard operands ride the zero-copy SHM
  rings at these sizes — every segment must be unlinked at shutdown).

Prints one JSON record; exits nonzero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_ROWS = 4096
D = 64
S_DIM = 32
SHARD_ROWS = 512         # 8 shard tasks of ~128 KiB — over the SHM
#                          threshold, so operands ride the rings
# SEED pins the ring placement as well as the data: at this plan
# fingerprint the 3-member ring owns shards [r1 r1 r0 r1 r2 r0 r2 r2],
# so the victim's SECOND task (shard 5) is the deterministic crash
# point mid-storm.
SEED = 42

CRASH_PLAN = json.dumps({"seed": 7, "faults": [
    {"site": "dist.shard", "crash": True, "on_hit": 2}]})


def _rows():
    return np.random.default_rng(SEED).standard_normal(
        (N_ROWS, D)).astype(np.float32)


def run_once(plan, src, ref) -> dict:
    """One fixed-seed storm: fresh 3-replica process fleet, victim
    ``r0`` armed with the crash plan, one ``submit_dist_sketch``
    through the router at ``pipeline=1`` (serialized dispatch — the
    chaos-determinism lever)."""
    from libskylark_tpu import fleet

    def victim_env(name):
        return ({"SKYLARK_FAULT_PLAN": CRASH_PLAN}
                if name == "r0" else None)

    pool = fleet.ReplicaPool(3, backend="process", max_batch=4,
                             replica_env=victim_env)
    router = fleet.Router(pool)
    try:
        failed = None
        result = None
        try:
            fut = router.submit_dist_sketch(plan, src, pipeline=1)
            result = fut.result(timeout=300)
        except Exception as e:  # noqa: BLE001 — a raise IS the failure
            failed = repr(e)
        co_stats = router.stats()["dist_coordinator"] or {}
        return {
            "failed": failed,
            "bit_equal": (result is not None
                          and bool(np.array_equal(result.SX, ref.SX))),
            "coverage": (None if result is None else result.coverage),
            "crashed": pool.crashed_names(),
            "dispatched": co_stats.get("dispatched"),
            "retried": co_stats.get("retried"),
            "reassigned": co_stats.get("reassigned"),
            "abandoned": co_stats.get("abandoned"),
        }
    finally:
        router.close()
        pool.shutdown()


def main() -> int:
    from libskylark_tpu import dist, engine
    from libskylark_tpu.fleet.shm import shm_entries

    A = _rows()
    plan = dist.ShardPlan(kind="cwt", n=N_ROWS, s_dim=S_DIM, d=D,
                          seed=SEED, shard_rows=SHARD_ROWS)
    src = dist.ArraySource(A)
    engine.reset()
    ref = dist.sketch_local(plan, src)
    shm_before = shm_entries()
    c0 = engine.stats().compiles
    violations = []

    runs = [run_once(plan, src, ref), run_once(plan, src, ref)]
    for i, r in enumerate(runs):
        if r["failed"]:
            violations.append(
                f"run {i}: client-visible failure: {r['failed']}")
        if not r["bit_equal"]:
            violations.append(
                f"run {i}: merged sketch not bit-equal to the one-shot "
                "sketch_local reference")
        if r["coverage"] != 1.0:
            violations.append(
                f"run {i}: coverage {r['coverage']} != 1.0 — shards "
                "were lost instead of reassigned")
        if r["crashed"] != ["r0"]:
            violations.append(
                f"run {i}: pool reaped {r['crashed']}, expected "
                "['r0'] (the crash-fault victim)")
        if not r["reassigned"]:
            violations.append(
                f"run {i}: the SIGKILL produced no shard reassignment")
        if r["abandoned"]:
            violations.append(
                f"run {i}: {r['abandoned']} shard(s) abandoned — the "
                "retry budget should have absorbed the crash")
    replay = {k: (runs[0][k], runs[1][k])
              for k in ("dispatched", "retried", "reassigned",
                        "abandoned")}
    if any(a != b for a, b in replay.values()):
        violations.append(
            f"recovery not replayable: fixed-seed runs disagree on "
            f"{replay}")

    compiles = engine.stats().compiles - c0
    if compiles:
        violations.append(
            f"{compiles} engine compile(s) in the measured window — "
            "dist-serve jobs must not touch the executable cache")
    leaked = [n for n in shm_entries() if n not in shm_before]
    if leaked:
        violations.append(
            f"/dev/shm leak: {leaked} outlived the fleets")

    rec = {
        "metric": "dist_serve_smoke",
        "n_rows": N_ROWS,
        "shards": plan.num_shards,
        "runs": runs,
        "replay": replay,
        "engine_compiles": compiles,
        "shm_leaked": leaked,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("dist-serve smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
