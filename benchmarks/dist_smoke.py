"""Dist smoke — the CI fault-tolerant-distributed-sketching gate
(docs/distributed).

Proves the shard-task contract over REAL process replicas, the
resilience tier the chaos battery's in-process dist leg cannot:

- **Leg A — SIGKILL mid-storm**: a 2-process-replica fleet where the
  victim child boots with a seeded ``SKYLARK_FAULT_PLAN`` carrying a
  ``crash`` spec at the ``dist.shard`` site (hard ``os._exit(137)``
  inside a shard task — the deterministic ``kill -9``, riding the
  pool's ``replica_env`` seat into ONE child, the r16 crash-fault
  discipline). The coordinator must reassign every in-flight and
  remaining shard of the corpse to the surviving peer and finish:
  full coverage, zero abandoned shards, final sketch **bit-equal** to
  the one-shot ``sketch_local`` reference (whose ingest is the
  ``io/chunked`` absolute batch grid), zero client-visible failures
  (``sketch()`` returns normally), the pool reaps the victim
  (``crashed_names()``), and zero engine compiles (shard tasks never
  touch the executable cache — chaos must not start compiles).

- **Leg B — forced abandonment**: an in-process coordinator under a
  fault plan that fails every shard-task attempt after the second hit
  with a one-retry budget: the ``min_coverage=1.0`` default must
  raise ``SketchCoverageError`` (never a silently-partial answer),
  and an explicit ``min_coverage=0.25`` must return a
  ``DegradedSketchResult`` whose coverage arithmetic is EXACT —
  rows merged, coverage fraction, coalesced missing row ranges.

Prints one JSON record; exits nonzero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_ROWS = 96
D = 8
S_DIM = 16
SHARD_ROWS = 12          # 8 shard tasks
SEED = 31

CRASH_PLAN = json.dumps({"seed": 7, "faults": [
    {"site": "dist.shard", "crash": True, "on_hit": 2}]})


def _rows():
    return np.random.default_rng(SEED).standard_normal(
        (N_ROWS, D)).astype(np.float32)


def _leg_crash(plan, src, ref) -> dict:
    from libskylark_tpu import dist, fleet

    def victim_env(name):
        # the crash spec rides into ONE child only — the surviving
        # peer must not inherit the chaos plan
        return ({"SKYLARK_FAULT_PLAN": CRASH_PLAN}
                if name == "r0" else None)

    pool = fleet.ReplicaPool(2, backend="process", max_batch=4,
                             replica_env=victim_env)
    try:
        co = dist.DistSketchCoordinator(pool, retries=3)
        failed = None
        result = None
        try:
            result = co.sketch(plan, src)
        except Exception as e:  # noqa: BLE001 — a raise IS the failure
            failed = repr(e)
        return {
            "failed": failed,
            "bit_equal": (result is not None
                          and bool(np.array_equal(result.SX, ref.SX))),
            "coverage": (None if result is None else result.coverage),
            "crashed": pool.crashed_names(),
            "stats": co.stats(),
        }
    finally:
        pool.shutdown()


def _leg_abandon(plan, src) -> dict:
    from libskylark_tpu import dist
    from libskylark_tpu.base import errors as sk_errors
    from libskylark_tpu.resilience import faults

    kill_plan = {"seed": 7, "faults": [
        {"site": "dist.shard", "error": "IOError_", "after": 2}]}
    co = dist.DistSketchCoordinator(retries=1, max_inflight=1)
    gate_raised = False
    with faults.fault_plan(kill_plan):
        try:
            co.sketch(plan, src)              # min_coverage default 1.0
        except sk_errors.SketchCoverageError:
            gate_raised = True
    co2 = dist.DistSketchCoordinator(retries=1, max_inflight=1)
    with faults.fault_plan(kill_plan):
        res = co2.sketch(plan, src, min_coverage=0.25)
    return {
        "gate_raised": gate_raised,
        "degraded_type": type(res).__name__,
        "coverage": res.coverage,
        "rows_merged": res.rows_merged,
        "missing": [list(r) for r in res.missing],
        "abandoned": co2.stats()["abandoned"],
    }


def main() -> int:
    from libskylark_tpu import dist, engine

    A = _rows()
    plan = dist.ShardPlan(kind="cwt", n=N_ROWS, s_dim=S_DIM, d=D,
                          seed=SEED, shard_rows=SHARD_ROWS)
    src = dist.ArraySource(A)
    engine.reset()
    # the one-shot reference: the same plan executed sequentially in
    # THIS process (io/chunked grid ingest, canonical merge tree)
    ref = dist.sketch_local(plan, src)
    violations = []

    crash_rec = _leg_crash(plan, src, ref)
    if crash_rec["failed"]:
        violations.append(
            f"crash leg: client-visible failure: {crash_rec['failed']}")
    if not crash_rec["bit_equal"]:
        violations.append(
            "crash leg: merged sketch not bit-equal to the one-shot "
            "sketch_local reference")
    if crash_rec["coverage"] != 1.0:
        violations.append(
            f"crash leg: coverage {crash_rec['coverage']} != 1.0 — "
            "shards were lost instead of reassigned")
    if crash_rec["crashed"] != ["r0"]:
        violations.append(
            f"crash leg: pool reaped {crash_rec['crashed']}, expected "
            "['r0'] (the crash-fault victim)")
    st = crash_rec["stats"]
    if st["reassigned"] < 1:
        violations.append(
            "crash leg: the SIGKILL produced no shard reassignment")
    if st["abandoned"]:
        violations.append(
            f"crash leg: {st['abandoned']} shard(s) abandoned — the "
            "retry budget should have absorbed the crash")

    abandon_rec = _leg_abandon(plan, src)
    if not abandon_rec["gate_raised"]:
        violations.append(
            "abandon leg: min_coverage=1.0 did not raise "
            "SketchCoverageError on a degraded merge")
    if abandon_rec["degraded_type"] != "DegradedSketchResult":
        violations.append(
            f"abandon leg: got {abandon_rec['degraded_type']}, "
            "expected DegradedSketchResult")
    # shards 0,1 complete (hits 1,2); shards 2..7 fail both attempts:
    # 24 rows merged of 96, missing = rows [24, 96)
    if (abandon_rec["rows_merged"] != 24
            or abandon_rec["coverage"] != 24 / 96
            or abandon_rec["missing"] != [[24, 96]]
            or abandon_rec["abandoned"] != 6):
        violations.append(
            f"abandon leg: coverage arithmetic wrong: {abandon_rec}")

    est = engine.stats()
    if est.compiles:
        violations.append(
            f"{est.compiles} engine compile(s) during the dist legs — "
            "shard tasks must not touch the executable cache")

    rec = {
        "metric": "dist_smoke",
        "n_rows": N_ROWS,
        "shards": plan.num_shards,
        "crash": crash_rec,
        "abandon": abandon_rec,
        "engine_compiles": est.compiles,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("dist smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
