"""Fleet smoke — the CI fleet gate's driver.

A 2-replica router run asserting the fleet subsystem's contract end
to end, fast enough for the per-commit gate:

- **warm-cache affinity**: after a capacity-ladder warmup, a measured
  storm routes with affinity hit-rate > 0.9 (sticky bounded-load
  ownership — in practice 1.0) and ZERO engine cache misses or
  recompiles. The affinity counter is what proves sticky routing:
  thread replicas share the one process-global executable cache, so
  the zero-miss check guards against compile thrash across routing,
  not against misrouting (only process replicas have per-replica
  caches where a misroute would surface as a miss);
- **correctness through the router**: every routed CWT result is
  bit-equal to the sequential ``transform.apply`` oracle (stream
  exactness survives routing);
- **clean drain-failover under an injected flush fault**: one replica
  drains mid-traffic (the per-replica preemption story) while a
  seeded ``serve.flush`` fault fires — bisection absorbs the fault,
  the router sheds the drained replica's traffic to its peer, and the
  gate asserts zero client-visible failures, zero orphaned futures,
  the drained replica off the ring, and its final drain hook fired;
- **autoscale round-trip from a warmup pack**: a 1-replica pool booted
  from a freshly built pack (cache reset in between, so the pack —
  not the builder's warm cache — supplies every executable) rides a
  throttled queue storm: the queue-depth controller scales up to 2
  (the new replica joins the router's ring via the SERVING publish),
  every storm future resolves bit-equal with zero client-visible
  failures and **zero backend compiles**, then sustained idleness
  drains the grown replica back away (the r11 SIGTERM-drain path) —
  also with zero failures. Leaked ``/dev/shm`` transport segments are
  asserted zero at exit.

Usage: ``python benchmarks/fleet_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys
from concurrent.futures import wait as cf_wait

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_REQUESTS = 32
MAX_BATCH = 8
CLASSES = (40, 96)          # two pow2 stream classes (pad 64 / 128)
S_DIM = 16

DRAIN_FAULT_PLAN = {
    "seed": 11,
    "faults": [
        # one transient flush fault during the drain-failover leg,
        # pinned to a tagged request the leg plants inside a
        # full-by-construction cohort: bisection must absorb it (both
        # halves re-execute clean), so it costs isolation retries but
        # zero client-visible failures. An unpinned on_hit=N spec
        # would make the gate timing-flaky: which flush attempt is
        # hit N depends on worker scheduling, and a singleton cohort
        # taking the hit cannot bisect — the client would see the
        # injected error with no code defect.
        {"site": "serve.flush", "error": "IOError_",
         "tag": "drain-poison", "times": 1},
    ],
}


def _autoscale_leg(violations) -> dict:
    """Queue storm -> scale-up observed -> idle -> scale-down drain,
    zero client-visible failures, zero compiles via the warmup pack
    (see module doc)."""
    import shutil
    import tempfile
    import time

    import jax.numpy as jnp

    from libskylark_tpu import Context, engine, fleet
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.engine import warmup
    from libskylark_tpu.resilience import faults

    rng = np.random.default_rng(1)
    ctx = Context(seed=0)
    T = sk.CWT(CLASSES[0], S_DIM, ctx)
    ops = [rng.standard_normal((CLASSES[0], 3 + i % 4))
           .astype(np.float32) for i in range(24)]
    refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for A in ops]

    pack_dir = tempfile.mkdtemp(prefix="skylark_fleet_pack_")
    rec: dict = {"pack_entries": None}
    try:
        spec = warmup.BucketSpec(
            endpoint="sketch_apply", family="CWT", n=CLASSES[0], m=6,
            s_dim=S_DIM, rowwise=False, capacities=(1, 2, 4, 8))
        manifest = warmup.build_pack(pack_dir, [spec])
        rec["pack_entries"] = len(manifest.get("entries", []))
        # reset: the pack, not the builder's warm cache, must supply
        # every executable the leg runs
        engine.reset()
        compiles0 = engine.stats().compiles
        pool = fleet.ReplicaPool(1, max_batch=MAX_BATCH,
                                 linger_us=2000, warmup_pack=pack_dir)
        router = fleet.Router(pool)
        scaler = fleet.Autoscaler(
            pool, router, min_replicas=1, max_replicas=2, up_depth=2,
            down_depth=1, up_ticks=1, down_ticks=4, cooldown_s=0.3,
            interval_s=0.05)
        try:
            # throttled storm: +10 ms per flush so the controller's
            # ticks deterministically observe the backlog
            plan = {"seed": 2, "faults": [
                {"site": "serve.flush", "stall_s": 0.01, "every": 1}]}
            failures = 0
            with faults.fault_plan(plan):
                futs = [router.submit_sketch(T, A)
                        for A in ops for _ in range(4)]
                deadline = time.monotonic() + 20
                while (time.monotonic() < deadline
                       and len(pool.names()) < 2):
                    time.sleep(0.05)
                scaled_up = len(pool.names()) == 2
                grown = [n for n in pool.names() if n != "r0"]
                if not scaled_up:
                    violations.append(
                        "autoscale leg: queue storm never scaled up")
                elif grown[0] not in router.routable():
                    violations.append(
                        "autoscale leg: grown replica never joined "
                        "the router ring")
                for i, f in enumerate(futs):
                    try:
                        out = f.result(timeout=120)
                    except Exception:  # noqa: BLE001 — counted
                        failures += 1
                        continue
                    if not np.array_equal(np.asarray(out),
                                          refs[i // 4]):
                        violations.append(
                            f"autoscale leg: request {i} diverged")
                        break
            if failures:
                violations.append(
                    f"autoscale leg: {failures} client-visible "
                    "failure(s) during scale-up storm")
            # idle: the controller must drain back to the floor
            deadline = time.monotonic() + 30
            while (time.monotonic() < deadline
                   and len(pool.names()) > 1):
                time.sleep(0.1)
            if len(pool.names()) != 1:
                violations.append(
                    "autoscale leg: idle fleet never scaled down")
            # post-shrink traffic still lands, still compile-free
            out = router.submit_sketch(T, ops[0]).result(timeout=60)
            if not np.array_equal(np.asarray(out), refs[0]):
                violations.append(
                    "autoscale leg: post-scale-down request diverged")
            compiles = engine.stats().compiles - compiles0
            if compiles:
                violations.append(
                    f"autoscale leg: {compiles} backend compile(s) — "
                    "the warmup pack did not cover the leg")
            st = scaler.stats()
            rec.update({
                "scaled_up": scaled_up,
                "scale_ups": st["scale_ups"],
                "scale_downs": st["scale_downs"],
                "client_visible_failures": failures,
                "compiles": compiles,
                "aot_loads": engine.stats().aot_loads,
                "replicas_final": len(pool.names()),
            })
        finally:
            scaler.close()
            router.close()
            pool.shutdown()
    finally:
        shutil.rmtree(pack_dir, ignore_errors=True)
    leaked = fleet.shm_entries()
    if leaked:
        violations.append(
            f"autoscale leg: leaked /dev/shm entries: {leaked}")
    return rec


def main() -> int:
    import jax.numpy as jnp

    from libskylark_tpu import Context, engine, fleet
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.resilience import faults

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    transforms = {n: sk.CWT(n, S_DIM, ctx) for n in CLASSES}
    reqs = []
    for i in range(N_REQUESTS):
        n = CLASSES[i % len(CLASSES)]
        A = rng.standard_normal((n, 3 + i % 4)).astype(np.float32)
        reqs.append((transforms[n], A))
    refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for (T, A) in reqs]

    engine.reset()
    violations = []
    # linger long enough that a mid-burst flusher expiry (which could
    # strand the drain leg's tagged request in an undersized cohort)
    # needs a >0.2 s stall between two adjacent submits — full cohorts
    # still dispatch immediately, so the storm legs never wait on it
    pool = fleet.ReplicaPool(2, max_batch=MAX_BATCH, linger_us=200_000)
    router = fleet.Router(pool)

    def storm():
        futs = [router.submit_sketch(T, A) for (T, A) in reqs]
        return [f.result(timeout=120) for f in futs]

    # -- warmup: the capacity ladder of both classes ---------------------
    for c_idx in range(len(CLASSES)):
        idxs = [i for i in range(N_REQUESTS)
                if i % len(CLASSES) == c_idx]
        cap = 1
        while cap <= MAX_BATCH:
            futs = [router.submit_sketch(*reqs[i]) for i in idxs[:cap]]
            [f.result(timeout=120) for f in futs]
            cap *= 2
    storm()

    # -- measured storm: warm affinity, zero compiles --------------------
    # engine.stats() returns the LIVE mutable counter object, so the
    # before-snapshot must capture the int, not the object
    misses_before = engine.stats().misses
    r0 = router.stats()
    outs = storm()
    st1 = engine.stats()
    r1 = router.stats()
    routed = r1["routed"] - r0["routed"]
    hits = r1["affinity_hit"] - r0["affinity_hit"]
    hit_rate = hits / routed if routed else 0.0
    misses = st1.misses - misses_before
    if hit_rate <= 0.9:
        violations.append(
            f"affinity hit-rate {hit_rate:.3f} <= 0.9 after warmup")
    if misses:
        violations.append(
            f"{misses} engine cache miss(es) on the warm fleet")
    if st1.recompiles:
        violations.append(
            f"{st1.recompiles} executable recompile(s) on the warm "
            "replica")
    for i, (o, ref) in enumerate(zip(outs, refs)):
        if not np.array_equal(np.asarray(o), ref):
            violations.append(
                f"request {i} not bit-equal to transform.apply "
                "through the router")
            break

    # -- drain-failover under an injected flush fault --------------------
    victim = router.owner_of("sketch_apply", transform=reqs[0][0],
                             A=reqs[0][1], dimension=None)
    by_replica_before = dict(r1["by_replica"])
    hooks = []
    pool.on_replica_drain(victim, lambda: hooks.append(victim))
    drain_failures = orphans = 0
    with faults.fault_plan(DRAIN_FAULT_PLAN):
        futs, exp = [], []
        # plant the tagged request inside a full-by-construction
        # cohort on the victim: MAX_BATCH same-class submits
        # back-to-back reach the fast path at capacity, and with the
        # tag at position 1 no realistic flusher-expiry fragmentation
        # can leave it in a singleton cohort (see DRAIN_FAULT_PLAN)
        burst = [reqs[2 * j] for j in range(MAX_BATCH)]
        for j, (T, A) in enumerate(burst):
            if j == 1:
                with faults.tag("drain-poison"):
                    futs.append(router.submit_sketch(T, A))
            else:
                futs.append(router.submit_sketch(T, A))
            exp.append(refs[2 * j])
        for i, (T, A) in enumerate(reqs):
            futs.append(router.submit_sketch(T, A))
            exp.append(refs[i])
            if i == N_REQUESTS // 4:
                drained = pool.preempt_replica(victim, timeout=60)
        fired = faults.fired()
        # bounded wait, THEN done-check: calling result() first would
        # make the orphan check unreachable (it either returns or
        # raises) — chaos_battery's _fleet_storm sets the idiom
        cf_wait(futs, timeout=120)
        for i, f in enumerate(futs):
            if not f.done():
                orphans += 1
            elif f.exception() is not None:
                drain_failures += 1
            elif not np.array_equal(np.asarray(f.result()), exp[i]):
                violations.append(
                    f"drain leg: request {i} diverged from oracle")
    if not drained:
        violations.append("victim replica did not drain to quiescence")
    if hooks != [victim]:
        violations.append(
            f"final drain hook fired {hooks!r}, expected [{victim!r}]")
    if drain_failures:
        violations.append(
            f"{drain_failures} client-visible failure(s) during the "
            "one-replica drain")
    if orphans:
        violations.append(f"{orphans} orphaned future(s)")
    if victim in router.routable():
        violations.append("drained replica still on the routing ring")
    if not fired:
        violations.append(
            "injected flush fault never fired — the drain-failover "
            "leg went inert (retune on_hit)")
    surviving = [n for n in pool.names() if n != victim]
    # delta across the drain leg only — the warmup ladder already
    # spread traffic over both replicas, so a whole-run count could
    # never catch a failover bug that black-holes post-drain traffic
    by_replica_after = router.stats()["by_replica"]
    absorbed = sum(
        by_replica_after.get(n, 0) - by_replica_before.get(n, 0)
        for n in surviving)
    if absorbed <= 0:
        violations.append(
            "no drain-leg traffic reached the surviving replica")

    router_stats = router.stats()
    replica_names = pool.names()
    router.close()
    pool.shutdown()

    # -- autoscale leg: pack-booted elastic pool -------------------------
    autoscale_rec = _autoscale_leg(violations)

    rec = {
        "metric": "fleet_smoke",
        "n_requests": N_REQUESTS,
        "replicas": replica_names,
        "router": router_stats,
        "affinity_hit_rate": round(hit_rate, 4),
        "misses_measured_window": misses,
        "recompiles": st1.recompiles,
        "drain_victim": victim,
        "drain_fault_fired": [list(f) for f in fired],
        "client_visible_failures": drain_failures,
        "autoscale": autoscale_rec,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("fleet smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
