"""FWHT-serve smoke — the CI gate for the panel-free SRHT tier.

A fast battery asserting the in-kernel FWHT contract end to end:

- **offline tuning**: every SRHT (bucket, capacity class) workload is
  ranked by the hardware-free cost model into an in-memory plan cache
  (the committed ``benchmarks/plan_cache.json`` is never touched); on
  a CPU host the decision must be "xla" for every bucket — the
  interpret penalty certifies the honest outcome off-silicon. The
  ``serve_cmm`` workload must enumerate exactly its one XLA candidate;
- **zero recompiles with selection enabled**: warm the capacity
  ladder, then two measured SRHT + compressed-matmul storms run with
  ZERO engine cache misses and ZERO recompiles;
- **dyadic bit-equality of the kernel path**: a forced-pallas
  (interpret-mode) SRHT flush on integer-lattice operands at
  ``n = 4^k``, ``s = 4^j`` is bit-equal to the capacity-1 forced-XLA
  dispatch, request by request — one flipped in-kernel Threefry sign
  or swapped sample coordinate would break it;
- **min-n decline accounting**: a transform below
  ``SKYLARK_FWHT_MIN_N`` under a pallas pin declines (counted reason)
  back to the XLA program, bit-equal to the reference;
- **compressed matmul**: the ``(estimate, bound)`` future resolves
  with the estimate inside the bound on well-conditioned data, and the
  sparse-A CWT lane is bit-equal to its densified twin.

Usage: ``python benchmarks/fwht_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_REQUESTS = 8
MAX_BATCH = 4
CAPACITIES = (1, 2, 4)
N_DIM, S_DIM = 4096, 256          # 4^6 / 4^4: the dyadic regime


def main() -> int:
    import jax
    import scipy.sparse as sp

    from libskylark_tpu import Context, engine, tune
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.sketch.fjlt import FJLT

    rng = np.random.default_rng(0)
    violations = []

    ts = [FJLT(N_DIM, S_DIM, Context(seed=i), fut="wht")
          for i in range(N_REQUESTS)]
    ops = [rng.integers(-4, 5, size=(5 + i % 4, N_DIM))
           .astype(np.float32) for i in range(N_REQUESTS)]
    t_cm = sk.CWT(1500, 256, Context(seed=77))
    cm_a = rng.standard_normal((30, 1500)).astype(np.float32)
    cm_b = rng.standard_normal((1500, 9)).astype(np.float32)

    engine.reset()
    prev_cache = tune.set_cache(tune.PlanCache(path=None))
    try:
        # -- offline tuning: SRHT ladder + the serve_cmm single lane ----
        decisions = {}
        for cap in CAPACITIES:
            w = tune.serve_workload(
                "sketch_apply", "SRHT", "float32", (8, N_DIM), S_DIM,
                cap, rowwise=True)
            plan, _cost = tune.record_ranked(w)
            ent = tune.get_cache().entry(w)
            decisions[f"srht_rw_8x{N_DIM}_s{S_DIM}/b{cap}"] = {
                "backend": plan.backend,
                "source": ent["source"] if ent else None,
            }
            if ent is None or ent.get("source") != "ranked":
                violations.append(
                    f"srht/b{cap}: no ranked plan-cache entry")
            if (jax.default_backend() != "tpu"
                    and plan.backend != "xla"):
                violations.append(
                    f"srht/b{cap}: tuner picked {plan.backend!r} on a "
                    "non-TPU host — the interpret penalty must "
                    "certify XLA off-silicon")
        w_cm = tune.serve_workload(
            "compressed_matmul", "CWT", "float32", (32, 1500), 256, 1,
            nnz=16)
        cm_cands = tune.enumerate_candidates(w_cm)
        if [p.backend for p in cm_cands] != ["xla"]:
            violations.append(
                "serve_cmm enumerated candidates beyond its one XLA "
                f"lane: {[p.backend for p in cm_cands]}")

        # -- selection enabled: warm ladder, then zero-compile storms ---
        ex = engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                       linger_us=5000,
                                       max_queue=8 * N_REQUESTS)

        def storm():
            futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                    for t, A in zip(ts, ops)]
            futs.append(ex.submit_compressed_matmul(cm_a, cm_b, t_cm))
            outs = [f.result(timeout=300) for f in futs]
            jax.block_until_ready(outs[:-1])
            return outs

        for cap in CAPACITIES:
            futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                    for t, A in zip(ts[:cap], ops[:cap])]
            ex.flush()
            [f.result(timeout=300) for f in futs]
        storm()
        misses_before = engine.stats().misses
        recompiles_before = engine.stats().recompiles
        sel_outs = storm()
        storm()
        misses = engine.stats().misses - misses_before
        recompiles = engine.stats().recompiles - recompiles_before
        fwht_flushes = ex.stats()["fwht"]
        ex.shutdown()
        if misses:
            violations.append(
                f"{misses} engine cache miss(es) after per-bucket "
                "warmup with selection enabled")
        if recompiles:
            violations.append(
                f"{recompiles} executable recompile(s) with selection "
                "enabled")
        if not fwht_flushes["by_backend"]:
            violations.append(
                "no SRHT flushes attributed — serve.fwht_flushes went "
                "inert")

        # -- dyadic bit-equality: forced kernel vs capacity-1 XLA -------
        with engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                       linger_us=5000,
                                       kernel="pallas") as exp:
            pfuts = [exp.submit_sketch(t, A, dimension=sk.ROWWISE)
                     for t, A in zip(ts, ops)]
            pouts = [np.asarray(f.result(timeout=600)) for f in pfuts]
            pstats = exp.stats()["fwht"]["by_backend"]
        if not pstats.get("pallas", {}).get("flushes"):
            violations.append(
                "forced-pallas executor served no pallas SRHT flushes "
                f"(by_backend={pstats})")
        with engine.MicrobatchExecutor(max_batch=1, linger_us=100,
                                       kernel="xla") as ex1:
            xouts = [np.asarray(ex1.submit_sketch(
                t, A, dimension=sk.ROWWISE).result(timeout=300))
                for t, A in zip(ts, ops)]
        for i, (p, x) in enumerate(zip(pouts, xouts)):
            if not np.array_equal(p, x):
                violations.append(
                    f"SRHT request {i}: in-kernel FWHT flush not "
                    "bit-equal to capacity-1 XLA dispatch on dyadic "
                    "operands")
                break
        for i, (s_out, x) in enumerate(zip(sel_outs, xouts)):
            if not np.array_equal(np.asarray(s_out), x):
                violations.append(
                    f"SRHT request {i}: selection-enabled flush not "
                    "bit-equal to capacity-1 XLA dispatch")
                break

        # -- min-n decline accounting under a pallas pin ----------------
        os.environ["SKYLARK_FWHT_KERNEL"] = "pallas"
        try:
            t_small = FJLT(1024, 64, Context(seed=91), fut="wht")
            a_small = rng.integers(-4, 5, size=(4, 1024)).astype(
                np.float32)
            with engine.MicrobatchExecutor(max_batch=1,
                                           linger_us=100) as exd:
                out = np.asarray(exd.submit_sketch(
                    t_small, a_small,
                    dimension=sk.ROWWISE).result(timeout=300))
                dstats = exd.stats()
        finally:
            del os.environ["SKYLARK_FWHT_KERNEL"]
        if not np.array_equal(
                out, np.asarray(t_small.apply(a_small, sk.ROWWISE))):
            violations.append("declined min-n flush diverged from the "
                              "transform's own apply")
        declined = dstats["kernel"]["by_reason"]
        if not any("fwht-min-n" in k.replace("_", "-")
                   for k in declined):
            violations.append(
                "no fwht-min-n decline counted under the pallas pin "
                f"(by_reason={declined})")
        if dstats["fwht"]["by_backend"].get("xla", {}).get(
                "flushes") != 1:
            violations.append(
                "declined flush not attributed to the xla backend "
                f"({dstats['fwht']['by_backend']})")

        # -- compressed matmul: bound + sparse/dense twin ---------------
        with engine.MicrobatchExecutor(max_batch=1,
                                       linger_us=100) as exc:
            est, bound = exc.submit_compressed_matmul(
                cm_a, cm_b, t_cm).result(timeout=300)
            err = float(np.linalg.norm(np.asarray(est) - cm_a @ cm_b))
            if err > bound:
                violations.append(
                    f"compressed matmul error {err:.3f} exceeded its "
                    f"bound {bound:.3f} on well-conditioned data")
            a_sp = sp.random(30, 1500, density=0.05, random_state=3,
                             dtype=np.float32, format="csr")
            es, _ = exc.submit_compressed_matmul(
                a_sp, cm_b, t_cm).result(timeout=300)
            ed, _ = exc.submit_compressed_matmul(
                a_sp.toarray(), cm_b, t_cm).result(timeout=300)
            if not np.array_equal(np.asarray(es), np.asarray(ed)):
                violations.append(
                    "sparse-A CWT compressed-matmul lane not bit-equal "
                    "to its densified twin")
            cm_count = exc.stats()["fwht"]["cm_submits"]
            if cm_count != 3:
                violations.append(
                    f"cm_submits counted {cm_count}, expected 3")
    finally:
        tune.set_cache(prev_cache)

    rec = {
        "metric": "fwht_smoke",
        "n_requests": N_REQUESTS,
        "n_dim": N_DIM,
        "s_dim": S_DIM,
        "decisions": decisions,
        "selection_flushes_by_backend": {
            k: v["flushes"]
            for k, v in fwht_flushes["by_backend"].items()},
        "forced_pallas_flushes_by_backend": {
            k: v["flushes"] for k, v in pstats.items()},
        "misses_after_warmup": misses,
        "recompiles_after_warmup": recompiles,
        "cm_error": err,
        "cm_bound": float(bound),
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("fwht smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
