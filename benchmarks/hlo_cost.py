"""Compiled-HLO cost analysis as a hardware-free perf regression artifact.

Three rounds of wedged TPU tunnel (VERDICT r4 weak #2) left the project
with no cross-round perf signal at all: CPU wall-clock drifts with the
host (EVIDENCE_r04.md) and on-chip numbers need a live window. XLA's
compiled cost model needs neither: for a fixed jitted computation at
fixed shapes, ``flops`` and ``bytes accessed`` are deterministic
properties of the lowered HLO — a dispatch change that materializes an
extra operator, doubles a contraction, or breaks a fusion shows up as a
step change in these numbers with zero hardware and zero timing noise.

Covers the BASELINE.md configs' XLA paths (the Pallas kernel itself is
chip-only — its guard is the on-chip oracle battery, not this file):

- jlt_xla: headline dense sketch apply (8192x8192 -> s=1024), XLA path
- rft:     GaussianRFT feature map (65536x256 -> 4096)
- frft:    FastGaussianRFT Fastfood chain (16384x4096 -> 4096)
- cwt:     sparse hash scatter at full scale (2^20 rows, nnz ~ 268k)
- svd:     randomized SVD (262144x512, k=10) end-to-end jit

``--save N`` writes benchmarks/hlo_cost_r{N:02d}.json; ``--gate``
compares against the newest committed hlo_cost_r*.json and exits 1 when
any shared config's flops or bytes grew >10% (new configs are free;
vanished configs fail). Run by script/ci — the drift-proof half of the
r5 perf ratchet (the canary-normalized wall-clock half lives in
run_all.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

# metrics whose growth the gate checks, with a 10% tolerance: flops and
# traffic are THE cost model; temp bytes catch a fusion break that
# spills an intermediate without changing either
GATED_KEYS = ("flops", "bytes_accessed", "temp_bytes")
TOLERANCE = 1.10


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _analyze(name, jitted, *avals) -> dict:
    # promoted into the package as the autotuner's compiled-HLO cost
    # oracle; this script keeps the CI-gate orchestration around it
    from libskylark_tpu.tune.cost import analyze_jitted

    return analyze_jitted(name, jitted, *avals)


def cfg_jlt_xla():
    """Headline config's XLA path: virtual-panel generation + one gemm
    (the sharded-apply workhorse; on TPU the Pallas kernel serves the
    eager single-device case instead)."""
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import JLT, ROWWISE
    from libskylark_tpu.sketch import params as sketch_params

    m, n, s = 8192, 8192, 1024
    T = JLT(n, s, Context(seed=3))
    prev = sketch_params.get_use_pallas()
    sketch_params.set_use_pallas(False)
    try:
        f = jax.jit(lambda X: T.apply(X, ROWWISE))
        return _analyze("jlt_xla", f, _sds((m, n)))
    finally:
        sketch_params.set_use_pallas(prev)


def cfg_rft():
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import ROWWISE
    from libskylark_tpu.sketch.rft import GaussianRFT

    n, d, s = 65536, 256, 4096
    T = GaussianRFT(d, s, Context(seed=2), sigma=2.0)
    f = jax.jit(lambda X: T.apply(X, ROWWISE))
    return _analyze("rft", f, _sds((n, d)))


def cfg_frft():
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import ROWWISE
    from libskylark_tpu.sketch.frft import FastGaussianRFT

    n, d, s = 16384, 4096, 4096
    T = FastGaussianRFT(d, s, Context(seed=9), sigma=2.0)
    f = jax.jit(lambda X: T.apply(X, ROWWISE))
    return _analyze("frft", f, _sds((n, d)))


def cfg_cwt():
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import CWT

    n, m, s = 1 << 20, 256, 4096
    nnz = 268435  # scipy.sparse.random(n, m, density=1e-3) nnz, fixed
    T = CWT(n, s, Context(seed=1))
    h, vals = T.bucket_indices(), T.values(jnp.float32)
    f = jax.jit(lambda r, c, v: jnp.zeros((s, m), v.dtype)
                .at[h[r], c].add(vals[r] * v))
    return _analyze("cwt", f, _sds((nnz,), jnp.int32),
                    _sds((nnz,), jnp.int32), _sds((nnz,)))


def cfg_svd():
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.nla.svd import approximate_svd

    m, n, k = 262144, 512, 10
    ctx = Context(seed=5)
    f = jax.jit(lambda A: approximate_svd(A, k, ctx))
    return _analyze("svd", f, _sds((m, n)))


CONFIGS = (cfg_jlt_xla, cfg_rft, cfg_frft, cfg_cwt, cfg_svd)


def _newest_prior(exclude: str | None) -> tuple[int, dict] | None:
    best = None
    for p in glob.glob(os.path.join(HERE, "hlo_cost_r*.json")):
        if exclude and os.path.abspath(p) == os.path.abspath(exclude):
            continue
        mm = re.search(r"hlo_cost_r(\d+)\.json$", p)
        if not mm:
            continue
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except Exception:
            continue
        rnd = int(mm.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, doc)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", type=int, metavar="ROUND", default=None)
    ap.add_argument("--gate", action="store_true")
    ap.add_argument("--only", default=None,
                    help="comma-separated config-name substrings")
    args = ap.parse_args()

    configs = CONFIGS
    if args.only:
        want = [s.strip() for s in args.only.split(",") if s.strip()]
        configs = tuple(c for c in configs
                        if any(w in c.__name__ for w in want))
        if not configs:
            sys.exit(f"--only {args.only!r} matched nothing")

    rows = []
    for cfg in configs:
        try:
            row = cfg()
        except Exception as e:
            row = {"config": cfg.__name__.removeprefix("cfg_"),
                   "error": f"{type(e).__name__}: {e}"}
        rows.append(row)
        print(json.dumps(row), flush=True)

    doc = {"backend": jax.default_backend(),
           "jax_version": jax.__version__,
           "results": rows}

    save_path = (os.path.join(HERE, f"hlo_cost_r{args.save:02d}.json")
                 if args.save is not None else None)
    prior = _newest_prior(exclude=save_path)

    if save_path:
        tmp = save_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
        os.replace(tmp, save_path)
        print(f"# saved {save_path}", file=sys.stderr)

    if args.gate:
        failures = []
        if prior is None:
            print("# gate: no prior hlo_cost_r*.json — nothing to "
                  "compare (first round records the baseline)",
                  file=sys.stderr)
            return
        rnd, pdoc = prior
        if pdoc.get("jax_version") != jax.__version__:
            # the cost model is XLA's own: a toolchain bump can move
            # every number without any repo change — report, don't fail
            print(f"# gate: prior r{rnd} used jax "
                  f"{pdoc.get('jax_version')}, this is {jax.__version__}"
                  " — comparison is informational only", file=sys.stderr)
        prior_rows = {r.get("config"): r
                      for r in pdoc.get("results", [])}
        ran = {r["config"] for r in rows}
        for name, prow in prior_rows.items():
            if args.only and name not in ran:
                continue  # a scoped run doesn't judge unran configs
            if "error" in prow:
                continue
            row = next((r for r in rows if r["config"] == name), None)
            if row is None or "error" in row:
                failures.append((name, "config vanished or now fails"))
                continue
            for key in GATED_KEYS:
                was, now = prow.get(key), row.get(key)
                if not was or now is None:
                    continue
                if now > was * TOLERANCE:
                    failures.append(
                        (name, f"{key} grew {now / was:.3f}x "
                               f"({was:.3e} -> {now:.3e})"))
        if failures and pdoc.get("jax_version") == jax.__version__:
            for name, why in failures:
                print(f"# HLO-COST REGRESSION {name}: {why}",
                      file=sys.stderr)
            sys.exit(1)
        for name, why in failures:
            print(f"# (informational) {name}: {why}", file=sys.stderr)


if __name__ == "__main__":
    main()
