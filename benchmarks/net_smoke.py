"""Net smoke — the CI network front-door gate's driver
(docs/networking).

A loopback TCP storm against a 2-replica fleet asserting the net
subsystem's contract end to end, fast enough for the per-commit gate:

- **wire transparency**: a 3-client loopback storm over cached
  digests returns every result bit-equal to the in-process
  ``Router.submit_sketch`` oracle with ZERO executable compiles in
  the measured window — the socket hop adds no numerics and no
  compilation;
- **retry idempotency**: a torn connection followed by the client's
  transparent reconnect-resend of the identical frame bytes lands on
  the router's single-flight/result-cache tier — the engine flushes
  EXACTLY once for the digest no matter how many times the wire tore;
- **chaos absorption**: an injected ``net.read`` fault (the fault
  table's socket site) tears a live server connection mid-stream and
  the client's bounded retry absorbs it with no caller-visible error;
- **SIGTERM drain**: the process preemption handler GOAWAYs every
  connection and flushes inflight responses — a burst submitted just
  before the signal resolves with ZERO client-visible failures.

Usage: ``python benchmarks/net_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_STORM = 60
N_CLIENTS = 3
N_UNIQUE = 4
MAX_BATCH = 8
CLASSES = (40, 96)          # two pow2 stream classes (pad 64 / 128)
S_DIM = 16
DRAIN_BURST = 12


def _fleet_cache_stats(pool) -> dict:
    from libskylark_tpu.engine import resultcache as rc

    blocks = [pool.get(n).executor.stats().get("cache")
              for n in pool.names()]
    merged = rc.merge_cache_blocks([b for b in blocks if b])
    merged["flushes"] = sum(
        pool.get(n).executor.stats()["flushes"] for n in pool.names())
    return merged


def main() -> int:
    import jax.numpy as jnp

    from libskylark_tpu import Context, engine, fleet, net
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.resilience import faults, preemption

    engine.reset()
    violations: list = []
    rng = np.random.default_rng(0)

    uniq = []
    for i in range(N_UNIQUE):
        n = CLASSES[i % len(CLASSES)]
        T = sk.CWT(n, S_DIM, Context(seed=i))
        A = rng.standard_normal((n, 3 + i)).astype(np.float32)
        uniq.append((T, A))

    pool = fleet.ReplicaPool(2, max_batch=MAX_BATCH, linger_us=2000,
                             cache=True)
    router = fleet.Router(pool, cache=True)
    srv = net.NetServer(router)
    clients = [net.NetClient(srv.address, retry_backoff_s=0.02, seed=i)
               for i in range(N_CLIENTS)]
    rec: dict = {"metric": "net_smoke", "n_storm": N_STORM,
                 "n_clients": N_CLIENTS, "n_unique": N_UNIQUE}
    try:
        # -- warmup + oracle: the IN-PROCESS path computes each unique
        # exactly once; the loopback storm must reproduce these bytes
        oracle = [np.asarray(
            router.submit_sketch(T, A).result(timeout=120))
            for (T, A) in uniq]
        deadline = time.monotonic() + 30
        while (_fleet_cache_stats(pool)["entries"] < N_UNIQUE
               and time.monotonic() < deadline):
            time.sleep(0.005)
        eng0 = engine.stats()
        compiles0 = (eng0.misses, eng0.recompiles)

        # -- leg 1: loopback storm, bit-equal + zero recompiles -------
        futs = []
        for i in range(N_STORM):
            T, A = uniq[i % N_UNIQUE]
            c = clients[i % N_CLIENTS]
            futs.append(c.submit("sketch_apply", transform=T, A=A,
                                 dimension=sk.COLUMNWISE))
        outs = [np.asarray(f.result(timeout=120)) for f in futs]
        eng1 = engine.stats()
        rec["recompiles_storm"] = (
            eng1.misses - compiles0[0], eng1.recompiles - compiles0[1])
        for i, out in enumerate(outs):
            if not np.array_equal(out, oracle[i % N_UNIQUE]):
                violations.append(
                    f"loopback request {i} diverged from the "
                    "in-process oracle")
                break
        if rec["recompiles_storm"] != (0, 0):
            violations.append(
                f"loopback storm compiled: misses/recompiles "
                f"{rec['recompiles_storm']}")
        ns = srv.stats()
        rec["requests_served"] = ns["requests"]
        if ns["requests"] < N_STORM:
            violations.append(
                f"server counted {ns['requests']} requests for a "
                f"{N_STORM}-request storm")

        # -- leg 2: torn connection + identical re-send -> one flush --
        c0 = clients[0]
        T2 = sk.CWT(CLASSES[0], S_DIM, Context(seed=41))
        A2 = rng.standard_normal((CLASSES[0], 5)).astype(np.float32)
        first = np.asarray(c0.submit(
            "sketch_apply", transform=T2, A=A2,
            dimension=sk.COLUMNWISE).result(timeout=120))
        deadline = time.monotonic() + 30
        while (_fleet_cache_stats(pool)["entries"] < N_UNIQUE + 1
               and time.monotonic() < deadline):
            time.sleep(0.005)
        flushes_before = _fleet_cache_stats(pool)["flushes"]
        with c0._lock:                       # tear the live socket
            sock = c0._sock
        sock.close()
        again = np.asarray(c0.submit(
            "sketch_apply", transform=T2, A=A2,
            dimension=sk.COLUMNWISE).result(timeout=120))
        st = _fleet_cache_stats(pool)
        rec["disconnect_retry"] = {
            "flushes_added": st["flushes"] - flushes_before,
            "bit_equal": bool(np.array_equal(first, again)),
        }
        if st["flushes"] != flushes_before:
            violations.append(
                f"disconnect+resend added "
                f"{st['flushes'] - flushes_before} flush(es) — the "
                "retry recomputed instead of hitting the cache")
        if not np.array_equal(first, again):
            violations.append("retried result diverged from original")

        # -- leg 3: chaos net.read fault absorbed by client retry -----
        # a FRESH client: the only frame read anywhere during the
        # plan is this request, so the fault (checked on frame
        # arrival, before dispatch) deterministically tears THIS
        # connection down pre-dispatch and the retry must happen
        T3 = sk.CWT(CLASSES[1], S_DIM, Context(seed=42))
        A3 = rng.standard_normal((CLASSES[1], 4)).astype(np.float32)
        cx = net.NetClient(srv.address, retry_budget=3,
                           retry_backoff_s=0.02, seed=7)
        plan = {"seed": 1, "faults": [
            {"site": "net.read", "error": "IOError_", "times": 1}]}
        try:
            with faults.fault_plan(plan):
                chaos_out = np.asarray(cx.submit(
                    "sketch_apply", transform=T3, A=A3,
                    dimension=sk.COLUMNWISE).result(timeout=120))
                fired = [f[0] for f in faults.fired()]
            retries = cx.client_stats()["transport_retries"]
        finally:
            cx.close()
        want = np.asarray(T3.apply(jnp.asarray(A3), sk.COLUMNWISE))
        rec["chaos"] = {"fired": fired, "transport_retries": retries,
                        "bit_equal": bool(np.array_equal(chaos_out,
                                                         want))}
        if fired != ["net.read"]:
            violations.append(f"chaos plan fired {fired}, expected "
                              "exactly one net.read hit")
        if not np.array_equal(chaos_out, want):
            violations.append("chaos-leg result diverged from oracle")
        if retries < 1:
            violations.append(
                "net.read fault did not exercise the transport retry")

        # -- leg 4: SIGTERM drain with zero client-visible failures ---
        preemption.install_preemption_handler()
        try:
            resp_before = srv.stats()["responses_sent"]
            # half the burst repeats cached digests, half is FRESH
            # work that must actually flush — so the drain has real
            # inflight computation to settle, not just queued hits
            work = []
            for i in range(DRAIN_BURST):
                if i % 2 == 0:
                    T, A = uniq[i % N_UNIQUE]
                else:
                    n = CLASSES[i % len(CLASSES)]
                    T = sk.CWT(n, S_DIM, Context(seed=50 + i))
                    A = rng.standard_normal((n, 4)).astype(np.float32)
                work.append((T, A))
            wants = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
                     for (T, A) in work]
            burst = [clients[i % N_CLIENTS].submit(
                "sketch_apply", transform=T, A=A,
                dimension=sk.COLUMNWISE) for i, (T, A) in
                enumerate(work)]
            # The drain contract flushes INFLIGHT requests; a frame
            # not yet handed to the router when drain_serving empties
            # the replica ring is legitimately refused with a
            # structured overload error. Pin determinism by waiting
            # until every burst request is inside the router —
            # pending (registered future) or already answered — which
            # only counts requests whose Router.submit has returned.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st = srv.stats()
                inside = (st["pending"]
                          + st["responses_sent"] - resp_before)
                if inside >= DRAIN_BURST:
                    break
                time.sleep(0.002)
            os.kill(os.getpid(), signal.SIGTERM)
            if not preemption.wait_for_preemption_teardown(60):
                violations.append("preemption teardown did not finish")
            failures = 0
            for i, fut in enumerate(burst):
                try:
                    out = np.asarray(fut.result(timeout=60))
                    if not np.array_equal(out, wants[i]):
                        failures += 1
                except Exception:  # noqa: BLE001 — any failure counts
                    failures += 1
            ns = srv.stats()
            rec["drain"] = {
                "burst": DRAIN_BURST,
                "client_visible_failures": failures,
                "drains": ns["drains"],
                "goaways_sent": ns["goaways_sent"],
                "draining": ns["draining"],
            }
            if failures:
                violations.append(
                    f"{failures} client-visible failure(s) across a "
                    "SIGTERM drain")
            if ns["drains"] < 1 or not ns["draining"]:
                violations.append("SIGTERM did not drain the server")
            if ns["goaways_sent"] < 1:
                violations.append("drain sent no GOAWAY frames")
        finally:
            preemption.uninstall_preemption_handler()
            preemption.reset_preemption()
    finally:
        for c in clients:
            c.close()
        srv.close()
        router.close()
        pool.shutdown()

    rec["violations"] = violations
    rec["ok"] = not violations
    print(json.dumps(rec), flush=True)
    if violations:
        for v in violations:
            print(f"NET GATE VIOLATION: {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
