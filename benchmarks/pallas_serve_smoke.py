"""Pallas-serve smoke — the CI kernel-selection gate's driver.

A 2-bucket serve mix asserting the r12 flush-kernel selection contract
end to end, fast enough for the per-commit gate:

- **offline tuning**: every (bucket, capacity class) workload of the
  mix is ranked by the hardware-free cost model (``tune.record_
  ranked``) into an in-memory plan cache — the committed
  ``benchmarks/plan_cache.json`` is never touched — and the gate
  asserts the cache then holds a ranked kernel decision (backend
  pallas|xla, source "ranked") for every bucket. On a CPU host the
  decision must be "xla" for every serve bucket: interpret-mode pallas
  is a correctness surface, not a speed surface, and the cost model's
  interpret penalty encodes exactly that (the honesty the committed
  bench record carries in prose);
- **zero recompiles with selection enabled**: a selection-enabled
  executor (``kernel=None`` — arg > env > plan cache > default) warms
  the capacity ladder of both buckets, then two measured storms run
  with ZERO engine cache misses and ZERO recompiles — the kernel
  choice is a static of the executable key resolved from a memoized
  (bucket, capacity, plan-fingerprint) triple, so steady-state
  selection can never retrace a warm bucket;
- **bit-equality of the kernel path**: a forced-pallas coalesced CWT
  flush (exact-accumulation under the interpreter) is bit-equal to
  the capacity-1 forced-XLA dispatch, request by request — the
  scatter-free kernel IS the scatter, bit for bit; the dense (JLT)
  kernel path is held to the serve layer's numerical oracle
  (allclose — its bf16x3 regime legitimately reorders f32 sums).

Usage: ``python benchmarks/pallas_serve_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_REQUESTS = 16          # per bucket
MAX_BATCH = 8
CAPACITIES = (1, 2, 4, 8)


def main() -> int:
    import jax

    from libskylark_tpu import Context, engine, tune
    from libskylark_tpu import sketch as sk

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    violations = []

    # -- the 2-bucket mix: CWT columnwise + JLT rowwise ------------------
    T_cwt = sk.CWT(40, 16, ctx)
    cwt_reqs = [(T_cwt,
                 rng.standard_normal((40, 3 + i % 4)).astype(np.float32))
                for i in range(N_REQUESTS)]
    jlt_reqs = []
    for i in range(N_REQUESTS):
        n = 112 + (i % 3) * 8
        T = sk.JLT(n, 32, ctx)
        A = rng.standard_normal((48 + (i % 4) * 4, n)).astype(np.float32)
        jlt_reqs.append((T, A))

    engine.reset()
    prev_cache = tune.set_cache(tune.PlanCache(path=None))
    try:
        # -- offline tuning: rank every (bucket, capacity) workload ------
        decisions = {}
        for cap in CAPACITIES:
            buckets = {
                f"cwt_cw_64x8_s16/b{cap}": tune.serve_workload(
                    "sketch_apply", "CWT", "float32", (64, 8), 16, cap,
                    rowwise=False),
                f"jlt_rw_64x128_s32/b{cap}": tune.serve_workload(
                    "sketch_apply", "JLT", "float32", (64, 128), 32,
                    cap, rowwise=True),
            }
            for bname, w in buckets.items():
                plan, _cost = tune.record_ranked(w)
                ent = tune.get_cache().entry(w)
                decisions[bname] = {
                    "backend": plan.backend,
                    "source": ent["source"] if ent else None,
                }
                if ent is None or ent.get("source") != "ranked":
                    violations.append(
                        f"{bname}: no ranked plan-cache entry after "
                        "record_ranked")
                elif ent["plan"]["backend"] not in ("pallas", "xla"):
                    violations.append(
                        f"{bname}: unranked backend "
                        f"{ent['plan']['backend']!r}")
                if (jax.default_backend() != "tpu"
                        and plan.backend != "xla"):
                    violations.append(
                        f"{bname}: tuner picked {plan.backend!r} on a "
                        "non-TPU host — the interpret penalty must "
                        "certify XLA off-silicon")

        # -- selection enabled: warm ladder, then zero-compile storms ----
        ex = engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                       linger_us=5000,
                                       max_queue=8 * N_REQUESTS)

        def storm():
            futs = ([ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                     for (T, A) in cwt_reqs]
                    + [ex.submit_sketch(T, A, dimension=sk.ROWWISE)
                       for (T, A) in jlt_reqs])
            outs = [f.result(timeout=120) for f in futs]
            jax.block_until_ready(outs)
            return outs

        for reqs, dim in ((cwt_reqs, sk.COLUMNWISE),
                          (jlt_reqs, sk.ROWWISE)):
            for cap in CAPACITIES:
                futs = [ex.submit_sketch(T, A, dimension=dim)
                        for (T, A) in reqs[:cap]]
                ex.flush()
                [f.result(timeout=120) for f in futs]
        storm()
        misses_before = engine.stats().misses
        recompiles_before = engine.stats().recompiles
        sel_outs = storm()
        storm()
        misses = engine.stats().misses - misses_before
        recompiles = engine.stats().recompiles - recompiles_before
        sel_flushes = ex.stats()["kernel"]["by_backend"]
        ex.shutdown()
        if misses:
            violations.append(
                f"{misses} engine cache miss(es) after per-bucket "
                "warmup with selection enabled")
        if recompiles:
            violations.append(
                f"{recompiles} executable recompile(s) with selection "
                "enabled")
        if not sel_flushes:
            violations.append(
                "selection-enabled executor counted no kernel flushes "
                "— the by_backend counter went inert")

        # -- bit-equality: forced kernel path vs capacity-1 XLA ----------
        with engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                       linger_us=5000,
                                       kernel="pallas") as exp:
            pfuts = ([exp.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                      for (T, A) in cwt_reqs]
                     + [exp.submit_sketch(T, A, dimension=sk.ROWWISE)
                        for (T, A) in jlt_reqs])
            pouts = [np.asarray(f.result(timeout=120)) for f in pfuts]
            pstats = exp.stats()["kernel"]["by_backend"]
        if not pstats.get("pallas", {}).get("flushes"):
            violations.append(
                "forced-pallas executor served no pallas flushes "
                f"(by_backend={pstats})")
        with engine.MicrobatchExecutor(max_batch=1, linger_us=100,
                                       kernel="xla") as ex1:
            xouts = []
            for (T, A) in cwt_reqs:
                xouts.append(np.asarray(ex1.submit_sketch(
                    T, A, dimension=sk.COLUMNWISE).result(timeout=120)))
            for (T, A) in jlt_reqs:
                xouts.append(np.asarray(ex1.submit_sketch(
                    T, A, dimension=sk.ROWWISE).result(timeout=120)))
        n_cwt = len(cwt_reqs)
        for i in range(n_cwt):
            if not np.array_equal(pouts[i], xouts[i]):
                violations.append(
                    f"CWT request {i}: kernel-path flush not bit-equal "
                    "to capacity-1 XLA dispatch")
                break
        # the dense-kernel oracle band (test_pallas_dense): the batched
        # kernel's bf16x3 regime reorders f32 sums the XLA vmapped path
        # accumulates exactly
        for i in range(n_cwt, len(pouts)):
            if not np.allclose(pouts[i], xouts[i], rtol=1e-4,
                               atol=1e-4):
                violations.append(
                    f"JLT request {i - n_cwt}: kernel-path flush "
                    "diverged from capacity-1 XLA dispatch")
                break
        for i in range(n_cwt):
            if not np.array_equal(np.asarray(sel_outs[i]), xouts[i]):
                violations.append(
                    f"CWT request {i}: selection-enabled flush not "
                    "bit-equal to capacity-1 XLA dispatch")
                break
    finally:
        tune.set_cache(prev_cache)

    rec = {
        "metric": "pallas_serve_smoke",
        "n_requests": 2 * N_REQUESTS,
        "max_batch": MAX_BATCH,
        "decisions": decisions,
        "selection_flushes_by_backend": {
            k: v["flushes"] for k, v in sel_flushes.items()},
        "forced_pallas_flushes_by_backend": {
            k: v["flushes"] for k, v in pstats.items()},
        "misses_after_warmup": misses,
        "recompiles_after_warmup": recompiles,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("pallas-serve smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
