"""Multi-tenant QoS smoke — the CI qos gate's driver (docs/qos).

A mixed 4-family traffic storm (CWT sketch + graph ASE + condest +
RLSC predict) across the three priority classes, asserting the QoS
contract end to end, fast enough for the per-commit gate:

- **priority isolation**: a best_effort storm past its pressure bound
  sheds (counted, ``>0``) while every interactive request in the same
  window completes with ZERO failures — the class-ordered shed policy
  that replaced the global shed;
- **zero recompiles after warmup**: the second storm runs with zero
  engine misses/recompiles — class separation rides the bucket key,
  never the executable key, so mixed-tenant traffic compiles nothing
  new;
- **adaptive retuning without a compile**: a manually-ticked
  controller (tight interactive SLO) changes linger/batch targets
  between the storms, and the target change itself introduces zero
  compiles — the targets only move along warm capacity rungs;
- **bit-equality per endpoint**: each family's storm results are
  bit-equal to capacity-1 dispatch through a fresh max_batch=1
  executor;
- **weighted fairness evidence**: the scheduler's served counters
  show every class drained (starvation freedom).

Usage: ``python benchmarks/qos_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

MAX_BATCH = 4
MAX_QUEUE = 32
N_DIM, S_DIM = 48, 16
GRAPH_N = 20
BE_STORM = 3 * MAX_QUEUE         # well past the 0.5 pressure bound


def _fail(rec, msg):
    rec["violation"] = msg
    print(json.dumps(rec), flush=True)
    return 1


def main() -> int:
    from libskylark_tpu import Context, engine, qos
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.ml import graph as mgraph
    from libskylark_tpu.ml.kernels import Gaussian
    from libskylark_tpu.qos.controller import AdaptiveController

    rng = np.random.default_rng(7)
    ctx = Context(seed=7)

    # the four traffic families
    T = sk.CWT(N_DIM, S_DIM, ctx)
    sketch_ops = [rng.standard_normal((N_DIM, 3 + i % 3))
                  .astype(np.float32) for i in range(8)]
    G = mgraph.Graph()
    for _ in range(4 * GRAPH_N):
        u, v = rng.integers(0, GRAPH_N, 2)
        G.add_edge(int(u), int(v))
    cond_ops = [rng.standard_normal((24, 10)).astype(np.float32)
                for _ in range(4)]
    Xtr = rng.standard_normal((12, 4)).astype(np.float32)
    coef = rng.standard_normal((12, 3)).astype(np.float32)
    rlsc_queries = [rng.standard_normal((5, 4)).astype(np.float32)
                    for _ in range(4)]
    gk = Gaussian(4, 1.0)

    reg = qos.TenantRegistry()
    reg.register("ui", qos.INTERACTIVE)
    reg.register("svc", qos.STANDARD)
    reg.register("etl", qos.BEST_EFFORT)

    ex = engine.MicrobatchExecutor(
        max_batch=MAX_BATCH, linger_us=1000, max_queue=MAX_QUEUE,
        workers=1, tenants=reg)
    ctrl = AdaptiveController(ex, start=False)

    def storm(count_sheds: bool):
        """One mixed storm: interactive sketch+condest+rlsc, standard
        graph, plus (optionally) a best_effort sketch burst past the
        pressure bound. Returns (futures-by-family, be_sheds,
        interactive_failures)."""
        futs = {"sketch": [], "graph_ase": [], "condest": [],
                "rlsc": []}
        be_sheds = 0
        interactive = []
        for i in range(8):
            f = ex.submit_sketch(T, sketch_ops[i % 8], tenant="ui")
            futs["sketch"].append(f)
            interactive.append(f)
        for s in range(3):
            futs["graph_ase"].append(
                ex.submit_graph_ase(G, 3, seed=s, tenant="svc"))
        for A in cond_ops:
            f = ex.submit_condest(A, steps=6, seed=1, tenant="ui")
            futs["condest"].append(f)
            interactive.append(f)
        for Xq in rlsc_queries:
            f = ex.submit_rlsc_predict(gk, Xq, Xtr, coef,
                                       tenant="ui")
            futs["rlsc"].append(f)
            interactive.append(f)
        if count_sheds:
            for i in range(BE_STORM):
                try:
                    futs["sketch"].append(ex.submit_sketch(
                        T, sketch_ops[i % 8], tenant="etl",
                        timeout=0.0))
                except engine.ServeOverloadedError:
                    be_sheds += 1
        ex.flush()
        failures = 0
        results = {}
        for fam, fs in futs.items():
            out = []
            for f in fs:
                try:
                    out.append(np.asarray(f.result(timeout=120)))
                except Exception:  # noqa: BLE001 — counted below
                    out.append(None)
                    if f in interactive:
                        failures += 1
            results[fam] = out
        return results, be_sheds, failures

    rec: dict = {"bench": "QOS_SMOKE", "max_batch": MAX_BATCH,
                 "max_queue": MAX_QUEUE}

    # ---- phase 0: deterministic capacity-ladder warmup per family —
    # the storm's cohort sizes are timing-dependent, so every rung a
    # cohort COULD land on must be compiled before the measured window
    for cap in (1, 2, 4):
        fs = [ex.submit_sketch(T, sketch_ops[i % 8], tenant="ui")
              for i in range(cap)]
        fs += [ex.submit_graph_ase(G, 3, seed=s, tenant="svc")
               for s in range(min(cap, 3))]
        fs += [ex.submit_condest(cond_ops[i % 4], steps=6, seed=1,
                                 tenant="ui") for i in range(cap)]
        fs += [ex.submit_rlsc_predict(gk, rlsc_queries[i % 4], Xtr,
                                      coef, tenant="ui")
               for i in range(cap)]
        ex.flush()
        [f.result(timeout=120) for f in fs]

    # ---- phase 1: warmup storm (exercises the mixed-flow paths)
    warm_results, _, warm_failures = storm(count_sheds=False)
    if warm_failures:
        return _fail(rec, f"{warm_failures} interactive failure(s) "
                     "during warmup")
    base = engine.stats().to_dict()

    # ---- adaptive retuning between the storms: tight interactive SLO
    os.environ["SKYLARK_QOS_SLO_INTERACTIVE_MS"] = "0.0001"
    os.environ["SKYLARK_QOS_SLO_STANDARD_MS"] = "0.0001"
    try:
        changes = 0
        for _ in range(4):
            changes += ctrl.tick()
            # fresh completions between ticks so hysteresis can act
            fs = [ex.submit_sketch(T, A, tenant="ui")
                  for A in sketch_ops]
            ex.flush()
            [f.result(timeout=120) for f in fs]
    finally:
        os.environ.pop("SKYLARK_QOS_SLO_INTERACTIVE_MS", None)
        os.environ.pop("SKYLARK_QOS_SLO_STANDARD_MS", None)
    rec["controller_changes"] = changes
    rec["targets"] = ex.stats()["qos"]["targets"]
    if changes < 1:
        return _fail(rec, "adaptive controller made no target change")

    # ---- phase 2: measured storm with the best_effort burst
    results, be_sheds, failures = storm(count_sheds=True)
    after = engine.stats().to_dict()
    rec["interactive_failures"] = failures
    rec["best_effort_sheds"] = be_sheds
    rec["misses_after_warmup"] = after["misses"] - base["misses"]
    rec["recompiles_after_warmup"] = (after["recompiles"]
                                      - base["recompiles"])
    if failures:
        return _fail(rec, f"{failures} interactive failure(s) during "
                     "the best_effort storm")
    if be_sheds < 1:
        return _fail(rec, "best_effort storm shed nothing — the "
                     "pressure bound is not engaging")
    if rec["misses_after_warmup"] or rec["recompiles_after_warmup"]:
        return _fail(rec, "engine compiled inside the measured storm "
                     "(adaptation or class separation leaked into "
                     "the executable key)")

    qstats = ex.stats()["qos"]
    rec["by_class"] = {
        c: {k: qstats["by_class"][c][k]
            for k in ("admitted", "shed", "rate_limited")}
        for c in qos.CLASSES}
    rec["served"] = qstats["scheduler"]["served"]
    if qstats["by_class"]["interactive"]["shed"]:
        return _fail(rec, "interactive requests were shed")
    if rec["served"]["interactive"] < 1:
        return _fail(rec, "scheduler served no interactive cohorts")

    # ---- bit-equality vs capacity-1 dispatch, per family
    ex1 = engine.MicrobatchExecutor(max_batch=1, linger_us=100,
                                    tenants=reg)
    try:
        cap1 = {
            "sketch": [np.asarray(ex1.submit_sketch(
                T, sketch_ops[i % 8]).result(timeout=120))
                for i in range(8)],
            "graph_ase": [np.asarray(ex1.submit_graph_ase(
                G, 3, seed=s).result(timeout=120)) for s in range(3)],
            "condest": [np.asarray(ex1.submit_condest(
                A, steps=6, seed=1).result(timeout=120))
                for A in cond_ops],
            "rlsc": [np.asarray(ex1.submit_rlsc_predict(
                gk, Xq, Xtr, coef).result(timeout=120))
                for Xq in rlsc_queries],
        }
    finally:
        ex1.shutdown()
    bit_equal = {}
    for fam, refs in cap1.items():
        got = [r for r in results[fam][: len(refs)] if r is not None]
        bit_equal[fam] = (len(got) == len(refs)
                          and all(np.array_equal(a, b)
                                  for a, b in zip(got, refs)))
    rec["bit_equal_to_capacity1"] = bit_equal
    ex.shutdown()
    if not all(bit_equal.values()):
        bad = [f for f, ok in bit_equal.items() if not ok]
        return _fail(rec, f"bit-equality vs capacity-1 broke: {bad}")

    rec["ok"] = True
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
