"""All BASELINE.md measurement configs, one JSON line each, with
per-round persistence and a regression gate.

``bench.py`` at the repo root is the driver-facing headline (config 1 at
full scale); this script measures every config so rounds can be compared
across the whole surface:

1. JLT dense sketch apply (GB/s, fused generation+matmul)
2. CWT sparse hash sketch on sparse input (M nnz/s)
2b. CWT on a MESH-DISTRIBUTED sparse input (P4/P5 path, M nnz/s)
3. FJLT + FastGaussianRFT feature maps (M rows/s)
4. Sketched least squares + randomized SVD (wall-clock)
5. KRR + Block-ADMM RLSC training (wall-clock)

Usage: python benchmarks/run_all.py [--scale small|full]
                                    [--save N] [--gate]
``--save N`` writes benchmarks/results_rN_<backend>.json; with prior
results_r*.json present, every metric is printed with its delta vs the
best prior round at the same backend+scale, and ``--gate`` exits nonzero when any metric regresses
by more than 10% (the perf ratchet for later rounds — the phase-timer
discipline of ref: ml/BlockADMM.hpp:357-365 made enforceable).

Every run also times a fixed pure-numpy CANARY kernel
(:func:`canary_seconds`) and records ``canary_normalized`` per metric:
the VM's host speed drifts ~1.5× across days (EVIDENCE_r04.md), so on
the CPU backend the gate compares canary-normalized ratios — a uniform
host-speed change cancels out and only genuine code/XLA-path
regressions trip it. On-chip ratios stay raw.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu even where a sitecustomize pre-imports jax with a
# pinned platform (post-import config update, same as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

# metric -> direction: +1 = higher is better (throughput),
#                      -1 = lower is better (wall-clock)
DIRECTIONS = {
    "jlt_sketch_apply_GBps": +1,
    "cwt_sparse_apply_Mnnz_per_s": +1,
    "cwt_dist_sparse_apply_Mnnz_per_s": +1,
    "rft_feature_map_Mrows_per_s": +1,
    "frft_feature_map_Mrows_per_s": +1,
    "nla_wallclock_s": -1,
    "admm_train_wallclock_s": -1,
}


def canary_seconds(reps: int = 7) -> float:
    """Best-of-``reps`` wall time of a FIXED pure-numpy compute kernel
    (deterministic shapes/seed; one 768³ f64 gemm + an elementwise
    chain). The VM's effective CPU speed drifts ~1.5× across days
    (EVIDENCE_r04.md host-speed drift study), so raw CPU-mesh ratios are
    not a valid cross-round signal; dividing/multiplying each metric by
    the same round's canary time cancels the host-speed factor for
    compute-bound workloads. On-chip numbers are NOT normalized — chip
    throughput doesn't ride the host clock (the canary is still
    recorded for provenance)."""
    rng = np.random.default_rng(12345)
    a = rng.standard_normal((768, 768))
    b = rng.standard_normal((768, 768))
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        c = a @ b
        c = np.tanh(c) + np.sqrt(np.abs(c) + 1.0)
        float(c.sum())
        best = min(best, time.perf_counter() - t0)
    return best


def _canary_norm(value: float, direction: int, canary_s: float) -> float:
    """Drift-normalized form of a metric value: throughput × canary_s
    (work per canary-unit of host time), wall-clock ÷ canary_s (walls in
    canary units). Both are invariant under a uniform host-speed change."""
    return value * canary_s if direction > 0 else value / canary_s


def _time_scalar(fn, *args, reps: int | None = None) -> float:
    """Best wall time of fn(*args) forced through a scalar readback.
    SKYLARK_BENCH_REPS raises the repeat count: the r4 variance study
    (EVIDENCE_r04.md) measured ±10% run-to-run spread for best-of-3 on
    the single-core CPU mesh — ratchet comparisons there should use
    more reps; on-chip runs are far less noisy and keep the default."""
    if reps is None:
        try:
            reps = int(os.environ.get("SKYLARK_BENCH_REPS", "3"))
        except ValueError:
            reps = 3
    out = fn(*args)
    float(out)  # warm + compile
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_jlt(scale: str):
    import bench

    # regime pinned explicitly and recorded: bench.run's default tracks
    # the shipping kernel regime, which may change between rounds — the
    # round-over-round ratchet needs a fixed, labeled regime
    precision = "bf16x3"
    if scale == "full":
        gbps, secs, plan = bench.run(precision=precision)
    else:
        gbps, secs, plan = bench.run(m=1024, n=1024, s=128, repeats=2,
                                     precision=precision)
    # plan_id top-level: every measurement names the plan that served it
    # (bench.run also feeds kernel measurements back into the tune/
    # plan cache — see bench._record_plan_measurement)
    return {"metric": "jlt_sketch_apply_GBps", "value": round(gbps, 3),
            "unit": "GB/s", "precision": precision, "plan": plan,
            "plan_id": plan.get("plan_id")}


def _sparse_input(scale: str):
    import scipy.sparse as sp

    from libskylark_tpu.base.sparse import SparseMatrix

    n, m, dens, s = ((1 << 20, 256, 1e-3, 4096) if scale == "full"
                     else (1 << 14, 64, 1e-2, 256))
    A = SparseMatrix.from_scipy(
        sp.random(n, m, density=dens, random_state=0, dtype=np.float64))
    return A, n, m, s


def bench_cwt_sparse(scale: str):
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import CWT

    A, n, m, s = _sparse_input(scale)
    T = CWT(n, s, Context(seed=1))
    f = jax.jit(lambda r, c, v: jnp.sum(jnp.abs(
        jnp.zeros((s, m), v.dtype).at[T.bucket_indices()[r], c].add(
            T.values(v.dtype)[r] * v))))
    r, c, v = A.coo()
    best = _time_scalar(f, r, c, v)
    return {"metric": "cwt_sparse_apply_Mnnz_per_s",
            "value": round(A.nnz / best / 1e6, 3), "unit": "Mnnz/s"}


def bench_cwt_dist_sparse(scale: str):
    """BASELINE config 2 on a MESH-DISTRIBUTED sparse input: the P4/P5
    path (shard_map local scatter + psum; ref:
    sketch/hash_transform_CombBLAS.hpp)."""
    from libskylark_tpu import parallel as par
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.base.dist_sparse import distribute_sparse
    from libskylark_tpu.sketch import COLUMNWISE, CWT

    A, n, m, s = _sparse_input(scale)
    n_dev = len(jax.devices())
    mesh = (par.square_mesh() if n_dev >= 4 else par.make_mesh())
    axes = (dict(row_axis="rows", col_axis="cols")
            if len(mesh.axis_names) > 1 and mesh.shape.get("cols", 1) > 1
            else dict(row_axis=mesh.axis_names[0]))
    D = distribute_sparse(A, mesh, **axes)
    T = CWT(n, s, Context(seed=1))
    f = jax.jit(lambda: jnp.sum(jnp.abs(T.apply(D, COLUMNWISE))))
    best = _time_scalar(f)
    return {"metric": "cwt_dist_sparse_apply_Mnnz_per_s",
            "value": round(A.nnz / best / 1e6, 3), "unit": "Mnnz/s",
            "devices": n_dev}


def bench_feature_maps(scale: str):
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.ml.kernels import Gaussian
    from libskylark_tpu.sketch import ROWWISE

    n, d, s = (65536, 256, 4096) if scale == "full" else (4096, 64, 512)
    X = jnp.asarray(np.random.default_rng(0).standard_normal((n, d)),
                    jnp.float32)
    out = {}
    for tag in ("regular", "fast"):
        T = Gaussian(d, sigma=2.0).create_rft(s, Context(seed=2), tag)
        f = jax.jit(lambda X: jnp.sum(jnp.abs(T.apply(X, ROWWISE))))
        best = _time_scalar(f, X)
        out[tag] = round(n / best / 1e6, 3)
    return {"metric": "rft_feature_map_Mrows_per_s", "value": out["regular"],
            "unit": "Mrows/s", "fast": out["fast"]}


def bench_frft(scale: str):
    """Fastfood at high input dimension — the regime it exists for
    (SHGΠHB beats the dense frequency-matrix GEMM,
    ref: sketch/FRFT_Elemental.hpp, sketch/FUT.hpp:225-347). The WHT core
    runs as the kron-factored MXU matmul (sketch/fut.py). Reported with
    the dense-RFT rows/s on the SAME config so the speedup is in the
    record (r2 finding: FRFT was 4× slower than RFT; the criterion is
    ≥2× faster at d ≥ 4096)."""
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import ROWWISE
    from libskylark_tpu.sketch.frft import FastGaussianRFT
    from libskylark_tpu.sketch.rft import GaussianRFT

    n, d, s = (16384, 4096, 4096) if scale == "full" else (2048, 512, 512)
    X = jnp.asarray(np.random.default_rng(8).standard_normal((n, d)),
                    jnp.float32)
    out = {}
    T_frft = FastGaussianRFT(d, s, Context(seed=9), sigma=2.0)
    for tag, T in (
        ("frft", T_frft),
        ("rft", GaussianRFT(d, s, Context(seed=9), sigma=2.0)),
    ):
        f = jax.jit(lambda X, T=T: jnp.sum(jnp.abs(T.apply(X, ROWWISE))))
        out[tag] = round(n / _time_scalar(f, X) / 1e6, 3)
    # whether the fused single-kernel chain (pallas_fastfood) served the
    # EAGER path on this backend; inside jit the dispatch sees a tracer
    # and takes the XLA chain, so also time the eager kernel path when
    # available — the record must say which path each number describes
    from libskylark_tpu.sketch import pallas_fastfood as pf

    rec = {"metric": "frft_feature_map_Mrows_per_s", "value": out["frft"],
           "unit": "Mrows/s", "rft_same_config": out["rft"],
           "speedup_vs_rft": round(out["frft"] / out["rft"], 3),
           "path": "xla_chain_jit"}
    if pf.supported(T_frft, X) and pf.features_rows(T_frft, X) is not None:
        # the probe call above matters: supported() checks the plan, but
        # Mosaic can still reject at compile time (features_rows then
        # returns None per its fallback contract) — that must leave the
        # already-measured XLA numbers intact, not crash the metric
        g = (lambda X: jnp.sum(jnp.abs(
            pf.features_rows(T_frft, X))))
        out["frft_fused_kernel"] = round(n / _time_scalar(g, X) / 1e6, 3)
        rec["fused_kernel_Mrows_per_s"] = out["frft_fused_kernel"]
        rec["fused_speedup_vs_rft"] = round(
            out["frft_fused_kernel"] / out["rft"], 3)
    return rec


def bench_nla(scale: str):
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.nla.least_squares import fast_least_squares
    from libskylark_tpu.nla.svd import approximate_svd

    m, n, k = (262144, 512, 10) if scale == "full" else (8192, 128, 6)
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    b = A @ jnp.asarray(rng.standard_normal(n), jnp.float32)

    t0 = time.perf_counter()
    x = fast_least_squares(A, b, Context(seed=4))
    x = x[0] if isinstance(x, tuple) else x
    float(jnp.sum(jnp.abs(x)))
    t_ls = time.perf_counter() - t0

    t0 = time.perf_counter()
    U, S, V = approximate_svd(A, k, Context(seed=5))
    float(jnp.sum(S))
    t_svd = time.perf_counter() - t0
    return {"metric": "nla_wallclock_s",
            "value": round(t_ls + t_svd, 3), "unit": "s",
            "least_squares_s": round(t_ls, 3), "svd_s": round(t_svd, 3)}


def bench_admm(scale: str):
    from libskylark_tpu.algorithms.prox import HingeLoss, L2Regularizer
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.ml.admm import BlockADMMSolver
    from libskylark_tpu.ml.kernels import Gaussian

    n, d, s, iters = ((16384, 128, 2048, 10) if scale == "full"
                      else (1024, 32, 256, 5))
    rng = np.random.default_rng(6)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    solver = BlockADMMSolver.from_kernel(
        Context(seed=7), HingeLoss(), L2Regularizer(), 0.01, s,
        Gaussian(d, sigma=3.0), num_partitions=4)
    solver.maxiter = iters
    solver.tol = 0.0
    t0 = time.perf_counter()
    solver.train(X, y)
    wall = time.perf_counter() - t0
    return {"metric": "admm_train_wallclock_s", "value": round(wall, 3),
            "unit": "s", "iters": iters}


def _prior_bests(scale: str, backend: str,
                 exclude: str | None = None
                 ) -> tuple[dict[str, float], dict[str, float]]:
    """One pass over results_r*.json → (best raw, best canary-normalized)
    value per metric, best respecting the metric's direction. Only
    rounds recorded at the SAME scale and backend are comparable — a
    full-scale TPU round must not gate a small-scale CPU run.
    ``exclude`` drops the round's OWN save file: on a --resume pass it
    matches the glob, and comparing a record against itself would
    overwrite its genuine cross-round ratio with a spurious 1.0.

    Normalization uses each RECORD's own ``canary_s`` (stored at
    measurement time, r5+) falling back to the file-level canary;
    records with neither can't be normalized and feed only the raw
    ratchet."""
    best: dict[str, float] = {}
    best_norm: dict[str, float] = {}
    for p in glob.glob(os.path.join(HERE, "results_r*.json")):
        if exclude is not None and os.path.abspath(p) == \
                os.path.abspath(exclude):
            continue
        try:
            with open(p) as fh:
                recs = json.load(fh)
        except Exception:
            continue
        if recs.get("scale") != scale or recs.get("backend") != backend:
            continue
        file_canary = recs.get("canary_s")
        for rec in recs.get("results", []):
            m, v = rec.get("metric"), rec.get("value")
            if m not in DIRECTIONS or not isinstance(v, (int, float)):
                continue
            d = DIRECTIONS[m]
            if m not in best or (v - best[m]) * d > 0:
                best[m] = v
            canary = rec.get("canary_s", file_canary)
            if isinstance(canary, (int, float)) and canary > 0:
                nv = _canary_norm(v, d, canary)
                if m not in best_norm or (nv - best_norm[m]) * d > 0:
                    best_norm[m] = nv
    return best, best_norm


def _existing_results(path: str, scale: str, backend: str) -> dict[str, dict]:
    """Metric → record from a previous (possibly partial) save of the same
    round at the same scale+backend, for carry-through and ``--resume``.
    A scale mismatch REFUSES the run outright: backend is in the filename
    but scale is not, so persisting would silently replace the other
    scale's round file (e.g. a --scale small spot-check destroying the
    full-scale TPU evidence captured through tunnel windows)."""
    try:
        with open(path) as fh:
            old = json.load(fh)
    except FileNotFoundError:
        return {}
    except Exception:
        sys.exit(f"refusing --save: {path} exists but is unreadable; "
                 "move it aside or pick another round number")
    if old.get("scale") != scale:
        sys.exit(f"refusing --save: {path} holds a scale="
                 f"{old.get('scale')!r} round; this run is scale={scale!r}."
                 " Pick another round number or move the file aside.")
    if old.get("backend") != backend:
        return {}
    out = {}
    for r in old.get("results", []):
        if not r.get("metric"):
            continue
        if (isinstance(r.get("value"), (int, float))
                and not isinstance(r.get("canary_s"), (int, float))
                and isinstance(old.get("canary_s"), (int, float))):
            # pre-per-record-canary save: attach the file-level canary
            # the values were measured under, so a --resume on a
            # different-speed day normalizes them correctly (and
            # _persist doesn't re-stamp them under today's canary)
            r = dict(r)
            r["canary_s"] = old["canary_s"]
        out[r["metric"]] = r
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="full")
    ap.add_argument("--save", type=int, metavar="ROUND", default=None,
                    help="persist results as results_rROUND_<backend>.json")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 if any metric regresses >10%% vs the "
                         "best prior round")
    ap.add_argument("--only", default=None,
                    help="comma-separated bench-name substrings or exact "
                         "metric names to run")
    ap.add_argument("--resume", action="store_true",
                    help="with --save: skip configs whose saved record "
                         "already has a non-null value (wedge recovery)")
    args = ap.parse_args()
    if args.resume and args.save is None:
        sys.exit("--resume requires --save (there is no file to resume "
                 "from or persist to)")

    regressed = []
    benches = (
        (bench_jlt, "jlt_sketch_apply_GBps"),
        (bench_cwt_sparse, "cwt_sparse_apply_Mnnz_per_s"),
        (bench_cwt_dist_sparse, "cwt_dist_sparse_apply_Mnnz_per_s"),
        (bench_feature_maps, "rft_feature_map_Mrows_per_s"),
        (bench_frft, "frft_feature_map_Mrows_per_s"),
        (bench_nla, "nla_wallclock_s"),
        (bench_admm, "admm_train_wallclock_s"),
    )
    if args.only:
        # bench-name substrings or EXACT metric names — substring matching
        # on metrics would make some benches unselectable alone
        # ("rft_feature_map_Mrows_per_s" is a substring of the frft metric)
        wanted = [s.strip() for s in args.only.split(",") if s.strip()]
        selected = [
            (fn, metric) for fn, metric in benches
            if any(s in fn.__name__ or s == metric for s in wanted)
        ]
        if not selected:
            names = ", ".join(f"{fn.__name__}/{m}" for fn, m in benches)
            sys.exit(f"--only {args.only!r} matched no bench "
                     f"(available: {names})")
        benches = tuple(selected)
    # backend in the filename: a round records the CPU-mesh and the
    # on-chip suites as separate artifacts (one path per round made
    # them overwrite each other); _prior_best reads both layouts
    save_path = (os.path.join(
        HERE, f"results_r{args.save:02d}_{jax.default_backend()}.json")
        if args.save is not None else None)
    # loaded whenever a save file exists: EVERY existing record is seeded
    # into the (metric-keyed, insertion-ordered) results map, so a kill
    # at any point — including mid-config on a wedged TPU — persists a
    # superset of what the file already held. Selected configs replace
    # their record in place when their measurement completes; --resume
    # additionally skips re-measuring selected configs already captured.
    existing = (_existing_results(save_path, args.scale,
                                  jax.default_backend())
                if save_path else {})
    results: dict[str, dict] = dict(existing)
    prior, prior_norm = _prior_bests(args.scale, jax.default_backend(),
                                     exclude=save_path)
    canary_s = round(canary_seconds(), 6)
    on_cpu = jax.default_backend() == "cpu"
    print(f"# canary_s={canary_s}", file=sys.stderr)

    def _persist():
        # after EVERY config, atomically: a tunnel wedge mid-suite must
        # not lose the configs already measured (the r3 wedge pattern —
        # windows of a few live minutes between multi-hour wedges)
        out = {"round": args.save, "scale": args.scale,
               "backend": jax.default_backend(),
               "canary_s": canary_s,
               "results": list(results.values())}
        tmp = save_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh, indent=1)
        os.replace(tmp, save_path)

    for fn, metric in benches:
        kept = existing.get(metric) if args.resume else None
        if kept is not None and kept.get("value") is not None:
            # resumed records fall through to the gate computation below —
            # a regression measured just before a wedge must still fail
            # the --gate run that resumes it (prior takes the BEST across
            # rounds, so the resumed value cannot mask itself)
            rec = dict(kept)
            rec["resumed"] = True
        else:
            try:
                rec = fn(args.scale)
            except Exception as e:  # record failure under its REAL metric
                rec = {"metric": metric, "value": None,
                       "error": f"{type(e).__name__}: {e}"}
            rec["backend"] = jax.default_backend()
            if isinstance(rec.get("value"), (int, float)):
                # the canary travels WITH the record: a --resume pass on
                # a different-speed day must normalize each value by the
                # canary measured alongside it, not by today's
                rec["canary_s"] = canary_s
        m, v = rec.get("metric"), rec.get("value")
        rec_canary = rec.get("canary_s")
        if (m in DIRECTIONS and isinstance(v, (int, float))
                and isinstance(rec_canary, (int, float))):
            rec["canary_normalized"] = round(
                _canary_norm(v, DIRECTIONS[m], rec_canary), 6)
        if m in DIRECTIONS and (m in prior or m in prior_norm):
            if isinstance(v, (int, float)):
                d = DIRECTIONS[m]
                gate_ratio = None
                if m in prior:
                    ratio = (v / prior[m]) if d > 0 else (prior[m] / v)
                    rec["vs_best_prior"] = round(ratio, 4)
                    gate_ratio = ratio
                if m in prior_norm and isinstance(rec_canary,
                                                 (int, float)):
                    nv = _canary_norm(v, d, rec_canary)
                    nratio = ((nv / prior_norm[m]) if d > 0
                              else (prior_norm[m] / nv))
                    rec["vs_best_prior_canary_norm"] = round(nratio, 4)
                    if on_cpu:
                        # on the CPU mesh the raw ratio confounds code
                        # changes with host-speed drift (r4 EVIDENCE);
                        # the normalized ratio is the gated signal there
                        gate_ratio = nratio
                if gate_ratio is not None and gate_ratio < 0.9:
                    regressed.append((m, gate_ratio))
            else:
                # a previously-measured config that now crashes is the
                # worst regression, not a free pass
                regressed.append((m, 0.0))
        held = results.get(metric)
        if (rec.get("value") is None and held is not None
                and held.get("value") is not None):
            # a failed RE-measurement must not destroy captured evidence:
            # keep the good record, note the failure alongside (the gate
            # above still saw the crash)
            err = rec.get("error") or "remeasure failed"
            rec = dict(held)
            rec["remeasure_error"] = err
        results[metric] = rec
        print(json.dumps(rec), flush=True)
        if save_path is not None:
            _persist()

    if save_path is not None:
        print(f"# saved {save_path}", file=sys.stderr)

    if args.gate and regressed:
        for m, r in regressed:
            print(f"# REGRESSION {m}: {r:.3f}x of best prior",
                  file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
