"""All five BASELINE.md measurement configs, one JSON line each.

``bench.py`` at the repo root is the driver-facing headline (config 1 at
full scale); this script measures every config so rounds can be compared
across the whole surface:

1. JLT dense sketch apply (GB/s, fused generation+matmul)
2. CWT sparse hash sketch on sparse input (M nnz/s)
3. FJLT + FastGaussianRFT feature maps (M rows/s)
4. Sketched least squares + randomized SVD (wall-clock)
5. KRR + Block-ADMM RLSC training (wall-clock)

Usage: python benchmarks/run_all.py [--scale small|full]
(small is CPU-friendly; full sizes target one TPU chip).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu even where a sitecustomize pre-imports jax with a
# pinned platform (post-import config update, same as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np


def _time_scalar(fn, *args, reps: int = 3) -> float:
    """Best wall time of fn(*args) forced through a scalar readback."""
    out = fn(*args)
    float(out)  # warm + compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_jlt(scale: str):
    import bench

    if scale == "full":
        gbps, secs = bench.run()
    else:
        gbps, secs = bench.run(m=1024, n=1024, s=128, repeats=2)
    return {"metric": "jlt_sketch_apply_GBps", "value": round(gbps, 3),
            "unit": "GB/s"}


def bench_cwt_sparse(scale: str):
    import scipy.sparse as sp

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.sketch import CWT, COLUMNWISE

    n, m, dens, s = ((1 << 20, 256, 1e-3, 4096) if scale == "full"
                     else (1 << 14, 64, 1e-2, 256))
    A = SparseMatrix.from_scipy(
        sp.random(n, m, density=dens, random_state=0, dtype=np.float64))
    T = CWT(n, s, Context(seed=1))
    f = jax.jit(lambda r, c, v: jnp.sum(jnp.abs(
        jnp.zeros((s, m), v.dtype).at[T.bucket_indices()[r], c].add(
            T.values(v.dtype)[r] * v))))
    r, c, v = A.coo()
    best = _time_scalar(f, r, c, v)
    return {"metric": "cwt_sparse_apply_Mnnz_per_s",
            "value": round(A.nnz / best / 1e6, 3), "unit": "Mnnz/s"}


def bench_feature_maps(scale: str):
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.ml.kernels import Gaussian
    from libskylark_tpu.sketch import ROWWISE

    n, d, s = (65536, 256, 4096) if scale == "full" else (4096, 64, 512)
    X = jnp.asarray(np.random.default_rng(0).standard_normal((n, d)),
                    jnp.float32)
    out = {}
    for tag in ("regular", "fast"):
        T = Gaussian(d, sigma=2.0).create_rft(s, Context(seed=2), tag)
        f = jax.jit(lambda X: jnp.sum(jnp.abs(T.apply(X, ROWWISE))))
        best = _time_scalar(f, X)
        out[tag] = round(n / best / 1e6, 3)
    return {"metric": "rft_feature_map_Mrows_per_s", "value": out["regular"],
            "unit": "Mrows/s", "fast": out["fast"]}


def bench_nla(scale: str):
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.nla.least_squares import fast_least_squares
    from libskylark_tpu.nla.svd import approximate_svd

    m, n, k = (262144, 512, 10) if scale == "full" else (8192, 128, 6)
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    b = A @ jnp.asarray(rng.standard_normal(n), jnp.float32)

    t0 = time.perf_counter()
    x = fast_least_squares(A, b, Context(seed=4))
    x = x[0] if isinstance(x, tuple) else x
    float(jnp.sum(jnp.abs(x)))
    t_ls = time.perf_counter() - t0

    t0 = time.perf_counter()
    U, S, V = approximate_svd(A, k, Context(seed=5))
    float(jnp.sum(S))
    t_svd = time.perf_counter() - t0
    return {"metric": "nla_wallclock_s",
            "value": round(t_ls + t_svd, 3), "unit": "s",
            "least_squares_s": round(t_ls, 3), "svd_s": round(t_svd, 3)}


def bench_admm(scale: str):
    from libskylark_tpu.algorithms.prox import HingeLoss, L2Regularizer
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.ml.admm import BlockADMMSolver
    from libskylark_tpu.ml.kernels import Gaussian

    n, d, s, iters = ((16384, 128, 2048, 10) if scale == "full"
                      else (1024, 32, 256, 5))
    rng = np.random.default_rng(6)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    solver = BlockADMMSolver.from_kernel(
        Context(seed=7), HingeLoss(), L2Regularizer(), 0.01, s,
        Gaussian(d, sigma=3.0), num_partitions=4)
    solver.maxiter = iters
    solver.tol = 0.0
    t0 = time.perf_counter()
    solver.train(X, y)
    wall = time.perf_counter() - t0
    return {"metric": "admm_train_wallclock_s", "value": round(wall, 3),
            "unit": "s", "iters": iters}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="full")
    args = ap.parse_args()
    for fn in (bench_jlt, bench_cwt_sparse, bench_feature_maps, bench_nla,
               bench_admm):
        rec = fn(args.scale)
        rec["backend"] = jax.default_backend()
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
