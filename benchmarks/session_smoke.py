"""Session smoke — the CI survivable-sessions gate (docs/sessions).

Proves the stateful-session contract over REAL process replicas, the
two resilience tiers the chaos battery's in-process leg cannot:

- **Leg A — SIGTERM drain handoff**: a CWT session owned by one
  process replica of a 2-replica fleet; mid-stream the owner gets a
  real SIGTERM (``ReplicaPool.preempt_replica`` — the child's r9
  preemption handler drains its executor, which checkpoints the live
  session), the owner leaves the router's ring so the next verb
  re-resolves ownership to the peer (fencing the drained owner's
  lease), the peer resumes from the checkpoint, and the stream
  continues. Asserts: the peer resumed from a *checkpoint* (not a
  full journal replay), at least one counted handoff, zero
  client-visible failures, finalize **bit-equal** to the one-shot
  sketch of the same row stream (the ``io.chunked.iter_array_batches``
  batching of it).

- **Leg B — crash-fault replay**: the owner child boots with a seeded
  ``SKYLARK_FAULT_PLAN`` carrying the ``crash`` spec (hard
  ``os._exit`` at the ``session.append`` site — the deterministic
  ``kill -9``, riding the pool's ``replica_env`` seat into ONE
  victim). The kill lands before the append is journaled; the
  client's same-seq retry replays onto the peer from the journal.
  Asserts: the pool reaped the crashed member
  (``crashed_names()``), an attached autoscaler replaced it back to
  the floor (the pack-boot replacement path), zero client-visible
  failures, finalize bit-equal.

Both legs also assert zero engine recompiles (sessions never touch
the executable cache — chaos must not start). Prints one JSON record;
exits nonzero on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_ROWS = 96
D = 8
S_DIM = 16
BATCH = 16
SEED = 29

CRASH_PLAN = json.dumps({"seed": 7, "faults": [
    {"site": "session.append", "crash": True, "on_hit": 3}]})


def _rows():
    return np.random.default_rng(SEED).standard_normal(
        (N_ROWS, D)).astype(np.float32)


def _reference(A):
    """The one-shot sketch of the same row stream: the session's
    io.chunked batching concatenates back to A, and the CWT session is
    bit-equal to the one-shot apply by construction."""
    import jax.numpy as jnp

    from libskylark_tpu import Context
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.io.chunked import iter_array_batches

    seen = [Xb for Xb, _ in iter_array_batches(A, BATCH)]
    assert np.array_equal(np.concatenate(seen), A)
    return np.asarray(sk.CWT(N_ROWS, S_DIM, Context(seed=SEED)).apply(
        jnp.asarray(A), sk.COLUMNWISE))


def _stream(router, pool, sid, A, *, preempt_after=None):
    """Drive the append stream with bounded same-seq retries; returns
    (client_visible_failures, retries)."""
    failures = retries = 0
    n_batches = N_ROWS // BATCH
    for i in range(n_batches):
        if preempt_after is not None and i == preempt_after:
            pool.preempt_replica(router.session_owner(sid))
        for _attempt in range(4):
            try:
                seq, rows = router.session_append(
                    sid, A[i * BATCH:(i + 1) * BATCH],
                    seq=i + 1).result(timeout=60.0)
                assert (seq, rows) == (i + 1, (i + 1) * BATCH)
                break
            except Exception:  # noqa: BLE001 — retry the same seq
                retries += 1
                time.sleep(0.2)
        else:
            failures += 1
    return failures, retries


def _leg_drain(A, ref) -> dict:
    from libskylark_tpu import fleet

    pool = fleet.ReplicaPool(2, backend="process", max_batch=4)
    router = fleet.Router(pool)
    try:
        sid = router.open_sketch_session(
            "cwt", n=N_ROWS, s_dim=S_DIM, d=D, seed=SEED, owner="r0")
        failures, retries = _stream(router, pool, sid, A,
                                    preempt_after=3)
        new_owner = router.session_owner(sid)
        peer_sessions = pool.get(new_owner).stats().get("sessions") or {}
        out = router.session_finalize(sid).result(timeout=60.0)
        return {
            "bit_equal": bool(np.array_equal(out["SX"], ref)),
            "client_visible_failures": failures,
            "retries": retries,
            "handoffs": router.stats()["session_handoffs"],
            "new_owner": new_owner,
            "peer_resumed": peer_sessions.get("resumed", 0),
            "peer_replayed_records":
                peer_sessions.get("replayed_records", 0),
        }
    finally:
        router.close()
        pool.shutdown()


def _leg_crash(A, ref) -> dict:
    from libskylark_tpu import fleet

    def victim_env(name):
        # the crash spec rides into ONE child only — the chaos plan
        # must not leak into the surviving peer
        return ({"SKYLARK_FAULT_PLAN": CRASH_PLAN}
                if name == "r0" else None)

    pool = fleet.ReplicaPool(2, backend="process", max_batch=4,
                             replica_env=victim_env)
    router = fleet.Router(pool)
    scaler = fleet.Autoscaler(pool, router, min_replicas=2,
                              max_replicas=3, interval_s=0.2,
                              cooldown_s=0.5)
    try:
        sid = router.open_sketch_session(
            "cwt", n=N_ROWS, s_dim=S_DIM, d=D, seed=SEED, owner="r0")
        failures, retries = _stream(router, pool, sid, A)
        out = router.session_finalize(sid).result(timeout=60.0)
        # the autoscaler must replace the reaped member back to the
        # floor (the pack-boot path — here pack-less, same verb)
        deadline = time.monotonic() + 120.0
        while (len(pool.names()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.2)
        return {
            "bit_equal": bool(np.array_equal(out["SX"], ref)),
            "client_visible_failures": failures,
            "retries": retries,
            "handoffs": router.stats()["session_handoffs"],
            "crashed": pool.crashed_names(),
            "replicas_after": pool.names(),
            "scale_ups": scaler.stats()["scale_ups"],
        }
    finally:
        scaler.close()
        router.close()
        pool.shutdown()


def main() -> int:
    import atexit
    import shutil

    from libskylark_tpu import engine

    scratch = tempfile.mkdtemp(prefix="skylark_session_smoke_")
    os.environ["SKYLARK_SESSION_DIR"] = scratch
    atexit.register(shutil.rmtree, scratch, ignore_errors=True)
    A = _rows()
    ref = _reference(A)
    engine.reset()
    violations = []

    drain_rec = _leg_drain(A, ref)
    if not drain_rec["bit_equal"]:
        violations.append(
            "drain leg: finalize not bit-equal to the one-shot sketch")
    if drain_rec["client_visible_failures"]:
        violations.append(
            f"drain leg: {drain_rec['client_visible_failures']} "
            "client-visible failure(s)")
    if drain_rec["handoffs"] < 1:
        violations.append("drain leg: no session handoff counted")
    if drain_rec["peer_resumed"] < 1:
        violations.append("drain leg: peer never resumed the session")
    if drain_rec["peer_replayed_records"]:
        violations.append(
            f"drain leg: peer replayed "
            f"{drain_rec['peer_replayed_records']} journal record(s) — "
            "the drain checkpoint did not cover the stream")

    crash_rec = _leg_crash(A, ref)
    if not crash_rec["bit_equal"]:
        violations.append(
            "crash leg: finalize not bit-equal to the one-shot sketch")
    if crash_rec["client_visible_failures"]:
        violations.append(
            f"crash leg: {crash_rec['client_visible_failures']} "
            "client-visible failure(s)")
    if crash_rec["crashed"] != ["r0"]:
        violations.append(
            f"crash leg: pool reaped {crash_rec['crashed']}, "
            "expected ['r0'] (the crash-fault victim)")
    if crash_rec["retries"] < 1:
        violations.append(
            "crash leg: the crash fault never fired (zero retries)")
    if len(crash_rec["replicas_after"]) < 2:
        violations.append(
            f"crash leg: autoscaler did not replace the dead member "
            f"(replicas: {crash_rec['replicas_after']})")
    if crash_rec["scale_ups"] < 1:
        violations.append("crash leg: no autoscaler replacement event")

    est = engine.stats()
    if est.recompiles:
        violations.append(
            f"{est.recompiles} engine recompile(s) during the "
            "session legs")

    rec = {
        "metric": "session_smoke",
        "n_rows": N_ROWS,
        "batch_rows": BATCH,
        "drain": drain_rec,
        "crash": crash_rec,
        "engine_recompiles": est.recompiles,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("session smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
