"""Sparse-serve smoke — the CI sparse-serve gate's driver.

A CSR serve mix asserting the sparse-operand hot-path contract
(docs/serving, "Sparse operands on the serve path") end to end, fast
enough for the per-commit gate:

- **offline tuning**: every (sparse bucket, capacity class) workload —
  keyed on the pow2 nnz class as well as the padded dims — is ranked
  by the nnz-aware cost model into an in-memory plan cache (the
  committed ``benchmarks/plan_cache.json`` is never touched), and on a
  CPU host the decision must be "xla" (the interpret penalty: the
  sparse kernel has no off-TPU speed surface);
- **ragged-nnz coalescing**: requests whose nnz differ inside one
  class land in ONE bucket and flush as one executable — asserted via
  ``request_statics`` identity, the coalesced counter, and ZERO engine
  misses/recompiles across two measured storms after the capacity-
  ladder warmup;
- **bit-equality**: every sparse flush (CWT and JLT, coalesced) is
  bit-equal to the densified reference — ``transform.apply(
  A.todense())`` — and to its own capacity-1 dispatch (lane
  invariance);
- **densify fallback**: an operand at or above
  ``SKYLARK_SPARSE_MIN_DENSITY`` routes through the dense endpoint and
  is counted (``sparse_densified``), still bit-equal;
- **sparse solve**: the CSR sketched-least-squares endpoint matches
  the dense serve solve on the densified operand bit for bit.

Usage: ``python benchmarks/sparse_smoke.py`` (script/ci wires
``JAX_PLATFORMS=cpu``). Prints one JSON record; exits nonzero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

N_REQUESTS = 16
MAX_BATCH = 8
CAPACITIES = (1, 2, 4, 8)
N_DIM, M_DIM, S_DIM = 512, 12, 16
NNZ_BASE = 40                    # class 64 at the default floor


def main() -> int:
    import jax
    import scipy.sparse as sp

    from libskylark_tpu import Context, engine, tune
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.engine.serve import request_statics

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    violations = []

    def rand_sparse(nnz, h=N_DIM, w=M_DIM):
        r = rng.integers(0, h, nnz)
        c = rng.integers(0, w, nnz)
        v = rng.standard_normal(nnz).astype(np.float32)
        return SparseMatrix.from_scipy(
            sp.coo_matrix((v, (r, c)), shape=(h, w)))

    # ragged nnz inside one class (floor 64): 33..56
    T_cwt = sk.CWT(N_DIM, S_DIM, ctx)
    cwt_reqs = [rand_sparse(33 + (i % 8) * 3) for i in range(N_REQUESTS)]
    T_jlt = sk.JLT(N_DIM, S_DIM, ctx)
    jlt_reqs = [rand_sparse(33 + (i % 8) * 3) for i in range(N_REQUESTS)]

    # -- bucket-key stability: one statics tuple across the ragged mix --
    keys = {request_statics("sparse_sketch_apply", transform=T_cwt,
                            A=A, dimension=sk.COLUMNWISE)
            for A in cwt_reqs}
    if len(keys) != 1:
        violations.append(
            f"ragged-nnz requests split into {len(keys)} buckets — the "
            "nnz class must coalesce one class into one bucket")
    k_small = request_statics("sparse_sketch_apply", transform=T_cwt,
                              A=rand_sparse(40),
                              dimension=sk.COLUMNWISE)
    k_large = request_statics("sparse_sketch_apply", transform=T_cwt,
                              A=rand_sparse(400),
                              dimension=sk.COLUMNWISE)
    if k_small == k_large:
        violations.append(
            "nnz classes 64 and 512 keyed identically — the nnz class "
            "is not in the bucket statics")

    engine.reset()
    prev_cache = tune.set_cache(tune.PlanCache(path=None))
    decisions = {}
    try:
        # -- offline tuning: rank every (bucket, capacity) workload ----
        for cap in CAPACITIES:
            w = tune.serve_workload(
                "sparse_sketch_apply", "CWT", "float32",
                (N_DIM, M_DIM), S_DIM, cap, rowwise=False,
                nnz=64)
            plan, _cost = tune.record_ranked(w)
            ent = tune.get_cache().entry(w)
            decisions[f"sparse_cwt/b{cap}"] = {
                "backend": plan.backend,
                "source": ent["source"] if ent else None,
            }
            if ent is None or ent.get("source") != "ranked":
                violations.append(
                    f"sparse_cwt/b{cap}: no ranked plan-cache entry")
            if (jax.default_backend() != "tpu"
                    and plan.backend != "xla"):
                violations.append(
                    f"sparse_cwt/b{cap}: tuner picked {plan.backend!r} "
                    "on a non-TPU host — the interpret penalty must "
                    "certify XLA off-silicon")

        # -- warm ladder, then zero-compile storms ---------------------
        ex = engine.MicrobatchExecutor(max_batch=MAX_BATCH,
                                       linger_us=5000,
                                       max_queue=8 * N_REQUESTS)

        def storm():
            futs = ([ex.submit_sparse(T_cwt, A, dimension=sk.COLUMNWISE)
                     for A in cwt_reqs]
                    + [ex.submit_sparse(T_jlt, A,
                                        dimension=sk.COLUMNWISE)
                       for A in jlt_reqs])
            outs = [f.result(timeout=120) for f in futs]
            jax.block_until_ready(outs)
            return outs

        for T, reqs in ((T_cwt, cwt_reqs), (T_jlt, jlt_reqs)):
            for cap in CAPACITIES:
                futs = [ex.submit_sparse(T, A, dimension=sk.COLUMNWISE)
                        for A in reqs[:cap]]
                ex.flush()
                [f.result(timeout=120) for f in futs]
        storm()
        misses_before = engine.stats().misses
        recompiles_before = engine.stats().recompiles
        outs = storm()
        storm()
        misses = engine.stats().misses - misses_before
        recompiles = engine.stats().recompiles - recompiles_before
        st = ex.stats()
        if misses:
            violations.append(
                f"{misses} engine cache miss(es) after per-bucket "
                "warmup on the sparse path")
        if recompiles:
            violations.append(
                f"{recompiles} executable recompile(s) on the warm "
                "sparse path")
        if not st["coalesced"]:
            violations.append("no coalesced sparse requests — the "
                              "ragged-nnz cohort never shared a flush")
        if not st["sparse"]["submits"]:
            violations.append("sparse submit counter inert")

        # -- bit-equality: densified reference + capacity-1 ------------
        refs = ([np.asarray(T_cwt.apply(A.todense(), sk.COLUMNWISE))
                 for A in cwt_reqs]
                + [np.asarray(T_jlt.apply(A.todense(), sk.COLUMNWISE))
                   for A in jlt_reqs])
        for i, (o, r) in enumerate(zip(outs, refs)):
            if not np.array_equal(np.asarray(o), r):
                violations.append(
                    f"request {i}: sparse flush not bit-equal to the "
                    "densified reference (todense -> transform.apply)")
                break
        with engine.MicrobatchExecutor(max_batch=1,
                                       linger_us=100) as ex1:
            for i, (T, A) in enumerate(
                    [(T_cwt, A) for A in cwt_reqs]
                    + [(T_jlt, A) for A in jlt_reqs]):
                one = np.asarray(ex1.submit_sparse(
                    T, A, dimension=sk.COLUMNWISE).result(timeout=120))
                if not np.array_equal(np.asarray(outs[i]), one):
                    violations.append(
                        f"request {i}: coalesced sparse flush not "
                        "bit-equal to capacity-1 dispatch")
                    break

        # -- densify fallback ------------------------------------------
        dense_ish = rand_sparse(int(N_DIM * M_DIM * 0.5))
        d0 = ex.stats()["sparse"]["densified"]
        fut = ex.submit_sparse(T_cwt, dense_ish,
                               dimension=sk.COLUMNWISE)
        got = np.asarray(fut.result(timeout=120))
        if ex.stats()["sparse"]["densified"] != d0 + 1:
            violations.append(
                "densify fallback not counted for a 50%-dense operand")
        if not np.array_equal(
                got, np.asarray(T_cwt.apply(dense_ish.todense(),
                                            sk.COLUMNWISE))):
            violations.append("densified fallback result diverged")

        # -- sparse solve ----------------------------------------------
        T_s = sk.CWT(64, 32, ctx)
        A_s = rand_sparse(30, h=64, w=6)
        B_s = rng.standard_normal((64, 2)).astype(np.float32)
        xs = np.asarray(ex.submit_sparse_solve(
            A_s, B_s, T_s).result(timeout=120))
        xd = np.asarray(ex.submit_solve(
            np.asarray(A_s.todense()), B_s, T_s).result(timeout=120))
        if not np.array_equal(xs, xd):
            violations.append(
                "sparse solve not bit-equal to the dense serve solve "
                "on the densified operand")
        ex.shutdown()
    finally:
        tune.set_cache(prev_cache)

    rec = {
        "metric": "sparse_serve_smoke",
        "n_requests": 2 * N_REQUESTS,
        "max_batch": MAX_BATCH,
        "decisions": decisions,
        "misses_after_warmup": misses,
        "recompiles_after_warmup": recompiles,
        "sparse_stats": st["sparse"],
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("sparse-serve smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
