"""North-star rehearsal: blocked randomized SVD at single-chip scale.

BASELINE.md's north star is a randomized SVD on a huge dense [MC,MR]
matrix within 1.5× of the reference stack's wall-clock at matched
accuracy (ref: nla/svd.hpp:227). Multi-chip hardware is not available, so
this script rehearses the two halves separately:

- ``--mode chip``: the largest dense matrix that fits one chip's HBM
  (default 32768×32768 f32 ≈ 4.3 GiB on a 16 GiB v5e) through
  ``approximate_svd`` — the panel-blocked lazy-operator apply keeps the
  sketch stage memory-bounded (sketch/dense.py auto-blocking; ref:
  dense_transform_Elemental_mc_mr.hpp blocked panel algorithm). Records
  wall-clock AND an accuracy gate.
- ``--mode mesh``: the same pipeline on an 8-device virtual CPU mesh with
  A sharded [MC,MR]-style — proves the collective pattern of the
  multi-chip path at small scale (the shapes are small; the sharding and
  psum structure are the multi-chip ones).

Accuracy gate: the test matrix is synthetic low-rank-plus-tail
(A = G1·diag(decay)·G2ᵀ with G1/G2 random orthonormal-ish Gaussian
panels), so the top singular values are known analytically to good
precision via the small (r0×r0) Gram problem; the gate checks the
recovered top-k singular values to ``--sv-rtol`` AND the projection
captures the dominant subspace (relative residual of A·V − U·S).

Writes one JSON record per mode; ``--save`` appends to
benchmarks/results_svd_scale_r{NN}.json (``--round``, default 4).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def _make_problem(n: int, r0: int, key, dtype):
    """A = G1 · diag(decay) · G2ᵀ, returned WITHOUT materializing more
    than one (n, n) array; also returns the reference top singular values
    computed from the small factors (exact up to the small-Gram SVD)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    k1, k2 = jax.random.split(jax.random.PRNGKey(int(key)))
    G1 = jax.random.normal(k1, (n, r0), dtype)
    G2 = jax.random.normal(k2, (n, r0), dtype)
    decay = jnp.asarray(0.9 ** jnp.arange(r0), dtype)
    A = (G1 * decay[None, :]) @ G2.T

    # exact singular values of the product via the small factors:
    # A = G1 D G2ᵀ; svd(A) shares singular values with
    # (R1 D R2ᵀ) where G1 = Q1 R1, G2 = Q2 R2 (r0×r0 problem on host).
    R1 = np.linalg.qr(np.asarray(G1, np.float64), mode="r")
    R2 = np.linalg.qr(np.asarray(G2, np.float64), mode="r")
    sv_true = np.linalg.svd(
        R1 @ np.diag(np.asarray(decay, np.float64)) @ R2.T,
        compute_uv=False)
    return A, sv_true


def _timed_svd(A, rank):
    """approximate_svd three ways: a COLD run (pays XLA compilation —
    recorded separately; the r4 profile's "~1.9s unattributed" at 8192²
    was exactly the cold wall minus the warm phases), a WARM unprofiled
    run whose wall is the headline (the overlapped-dispatch pipeline,
    compile cache hot — the number comparable to the reference's
    steady-state wall), then a PROFILED pass for the sketch /
    power-iteration / Rayleigh-Ritz split the north-star extrapolation
    needs (BASELINE.md). Timer state is restored whatever happens, so a
    crashed config can't leave the process-wide profiler on for later
    configs."""
    import time

    import jax.numpy as jnp

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.nla.svd import approximate_svd
    from libskylark_tpu.utility import timer as sk_timer

    t0 = time.perf_counter()
    U, S, V = approximate_svd(A, rank, Context(seed=19))
    float(jnp.sum(S))  # force completion through a readback
    cold = time.perf_counter() - t0

    t0 = time.perf_counter()
    U, S, V = approximate_svd(A, rank, Context(seed=19))
    float(jnp.sum(S))
    wall = time.perf_counter() - t0

    prev_enabled = sk_timer.timers_enabled()
    t = sk_timer.get_timer("svd")
    prev_totals, prev_counts = dict(t.totals), dict(t.counts)
    sk_timer.set_enabled(True)
    t.reset()
    try:
        U, S, V = approximate_svd(A, rank, Context(seed=19))
        float(jnp.sum(S))
        phases = {k: round(v, 3) for k, v in t.totals.items()}
        phases["note"] = "separate profiled pass (per-phase sync)"
        phases["cold_wall_s"] = round(cold, 3)
    finally:
        sk_timer.set_enabled(prev_enabled)
        t.totals, t.counts = prev_totals, prev_counts
    return U, S, V, wall, phases


def run_chip(n: int, rank: int, sv_rtol: float, res_gate: float):
    import jax
    import jax.numpy as jnp
    import numpy as np

    dtype = jnp.float32
    r0 = 4 * rank
    t0 = time.perf_counter()
    A, sv_true = _make_problem(n, r0, key=17, dtype=dtype)
    jax.block_until_ready(A)
    t_gen = time.perf_counter() - t0

    U, S, V, t_svd, phases = _timed_svd(A, rank)

    # accuracy gate 1: top singular values vs the analytic reference
    S_np = np.asarray(S, np.float64)
    rel = np.abs(S_np - sv_true[:rank]) / sv_true[:rank]
    sv_err = float(rel.max())

    # accuracy gate 2: A·V ≈ U·S (the factorization is consistent with A)
    AV = A @ V
    res = float(jnp.linalg.norm(AV - U * S[None, :]) /
                jnp.linalg.norm(AV))

    gate_ok = sv_err <= sv_rtol and res <= res_gate
    return {
        "metric": "svd_scale_wallclock_s",
        "mode": "chip",
        "backend": jax.default_backend(),
        "n": n, "rank": rank,
        "value": round(t_svd, 3), "unit": "s",
        "gen_s": round(t_gen, 3),
        "phases_s": phases,
        "sv_rel_err_max": round(sv_err, 6),
        "factorization_rel_res": round(res, 6),
        "accuracy_gate": "pass" if gate_ok else "FAIL",
        "hbm_bytes_A": 4 * n * n,
    }


def run_mesh(n: int, rank: int, sv_rtol: float, res_gate: float):
    """Same pipeline with A sharded over a (2, 4) mesh — the [MC,MR]
    2D-grid analog (P1) — so every stage (sketch apply, power iteration
    gemms, QR) compiles and executes against multi-device shardings."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from libskylark_tpu import parallel as par

    mesh = par.make_mesh((2, 4))
    dtype = jnp.float32
    r0 = 4 * rank
    A, sv_true = _make_problem(n, r0, key=17, dtype=dtype)
    A = jax.device_put(A, NamedSharding(mesh, P("rows", "cols")))

    with mesh:
        U, S, V, t_svd, phases = _timed_svd(A, rank)

    S_np = np.asarray(S, np.float64)
    rel = np.abs(S_np - sv_true[:rank]) / sv_true[:rank]
    sv_err = float(rel.max())
    AV = A @ V
    res = float(jnp.linalg.norm(AV - U * S[None, :]) /
                jnp.linalg.norm(AV))
    gate_ok = sv_err <= sv_rtol and res <= res_gate
    return {
        "metric": "svd_scale_wallclock_s",
        "mode": "mesh",
        "backend": "cpu",
        "devices": 8,
        "n": n, "rank": rank,
        "value": round(t_svd, 3), "unit": "s",
        "phases_s": phases,
        "sv_rel_err_max": round(sv_err, 6),
        "factorization_rel_res": round(res, 6),
        "accuracy_gate": "pass" if gate_ok else "FAIL",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["chip", "mesh"], required=True)
    ap.add_argument("--n", type=int, default=None,
                    help="matrix side (default: 32768 chip, 1024 mesh)")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--sv-rtol", type=float, default=1e-2)
    ap.add_argument("--res-gate", type=float, default=1e-3)
    ap.add_argument("--save", action="store_true",
                    help="append to results_svd_scale_r{round}.json")
    ap.add_argument("--round", type=int, default=4,
                    help="round number for the --save filename")
    args = ap.parse_args()

    if args.mode == "chip":
        rec = run_chip(args.n or 32768, args.rank, args.sv_rtol,
                       args.res_gate)
    else:
        rec = run_mesh(args.n or 1024, args.rank, args.sv_rtol,
                       args.res_gate)
    print(json.dumps(rec), flush=True)
    if args.save:
        path = os.path.join(HERE, f"results_svd_scale_r{args.round:02d}.json")
        recs = []
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    recs = json.load(fh)
            except Exception:
                # a file torn by an earlier SIGTERM mid-write must not
                # brick every later save — preserve the evidence of the
                # tear, start the list fresh
                os.replace(path, path + ".corrupt")
        key = (rec["mode"], rec["n"], rec["rank"])
        recs = [r for r in recs
                if (r.get("mode"), r.get("n"), r.get("rank")) != key] + [rec]
        # atomic: the watcher runs this under `timeout`, and a SIGTERM
        # between a truncating open and the dump's end would destroy the
        # other mode's captured record
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(recs, fh, indent=1)
        os.replace(tmp, path)
    if rec["accuracy_gate"] != "pass":
        sys.exit(1)


if __name__ == "__main__":
    main()
