"""Telemetry smoke battery: the CI gate for the observability contract.

Runs a small serve workload with telemetry enabled (JSONL export into a
temp dir), including a tag-pinned poison request under a deterministic
``serve.flush`` fault plan so the bisection-isolation path traces too,
then asserts the exported artifacts:

1. **JSONL schema**: every line in every ``spans-*.jsonl`` /
   ``metrics-*.jsonl`` parses and carries the documented required
   fields (docs/observability.rst).
2. **Span-tree well-formedness**: every non-null ``parent_id`` resolves
   to an exported span (no orphan parents), and no span is its own
   ancestor.
3. **End-to-end request trace**: the request id attached at
   ``submit()`` appears on that request's ``serve.submit`` span, on the
   ``serve.flush`` span of its cohort (which parents under the submit
   span — the cross-thread handoff), and on every
   ``serve.isolation`` retry span whose half contained it.
4. **Unified Prometheus surface**: ``telemetry.prometheus_text()``
   exposes the engine, serve, and resilience counters under the
   ``skylark_`` naming scheme.

Prints one JSON summary line; exits nonzero on any violation. Run by
``script/ci`` (the disabled-mode overhead check lives in the serve
gate, which compares a telemetry-off ``bench.py --serve`` against the
committed r8 record).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_TDIR = tempfile.mkdtemp(prefix="skylark_telemetry_smoke_")
os.environ["SKYLARK_TELEMETRY_DIR"] = _TDIR  # before libskylark import

# Hardware-independent; default to CPU unless the caller pinned a
# platform (the conftest discipline).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from libskylark_tpu import Context, engine, telemetry  # noqa: E402
from libskylark_tpu import sketch as sk  # noqa: E402
from libskylark_tpu.resilience import faults  # noqa: E402

REQUIRED_SPAN_FIELDS = ("kind", "name", "trace_id", "span_id",
                        "t_wall", "duration_s", "status", "thread")


def fail(msg: str) -> None:
    print(json.dumps({"metric": "telemetry_smoke", "ok": False,
                      "violation": msg}))
    sys.exit(1)


def run_workload() -> tuple:
    """A coalesced cohort with one tag-pinned poison request; returns
    (poison request id, clean request ids)."""
    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    reqs = [(sk.JLT(48, 16, ctx),
             rng.standard_normal((48, 3 + i)).astype(np.float32))
            for i in range(4)]
    plan = {"seed": 1, "faults": [
        {"site": "serve.flush", "error": "SketchError", "tag": "poison"}]}
    clean_ids = [f"req-smoke-clean-{i}" for i in range(3)]
    poison_id = "req-smoke-poison"
    with engine.MicrobatchExecutor(max_batch=4, linger_us=50_000) as ex:
        with faults.fault_plan(plan):
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE,
                                     request_id=rid)
                    for (T, A), rid in zip(reqs[:3], clean_ids)]
            with faults.tag("poison"):
                pT, pA = reqs[3]
                pf = ex.submit_sketch(pT, pA, dimension=sk.COLUMNWISE,
                                      request_id=poison_id)
            ex.flush()
            for f in futs:
                f.result(timeout=120)  # cohort-mates must succeed
            try:
                pf.result(timeout=120)
                fail("poison request unexpectedly succeeded")
            except Exception as e:  # noqa: BLE001 — the expected poison
                if type(e).__name__ != "SketchError":
                    fail(f"poison failed with {type(e).__name__}, "
                         f"expected SketchError")
    exporter = telemetry.get_exporter()
    if exporter is None:
        fail("SKYLARK_TELEMETRY_DIR set but no exporter installed")
    exporter.flush_sync()
    return poison_id, clean_ids


def load_lines(pattern: str) -> list:
    docs = []
    for path in sorted(glob.glob(os.path.join(_TDIR, pattern))):
        with open(path) as fh:
            for i, line in enumerate(fh):
                try:
                    docs.append(json.loads(line))
                except json.JSONDecodeError:
                    fail(f"{os.path.basename(path)}:{i + 1} is not "
                         f"valid JSON")
    return docs


def validate_schema(spans: list, metric_lines: list) -> None:
    for doc in spans:
        missing = [f for f in REQUIRED_SPAN_FIELDS if f not in doc]
        if missing:
            fail(f"span line missing fields {missing}: "
                 f"{json.dumps(doc)[:200]}")
        if doc["kind"] != "span":
            fail(f"spans file carries kind={doc['kind']!r}")
        if doc["status"] not in ("ok", "error"):
            fail(f"span status {doc['status']!r} not ok|error")
    if not metric_lines:
        fail("no metrics lines exported")
    for doc in metric_lines:
        if doc.get("kind") != "metrics" or "snapshot" not in doc:
            fail("metrics line missing kind/snapshot")
        collectors = doc["snapshot"].get("collectors", {})
        for want in ("engine", "serve"):
            if want not in collectors:
                fail(f"metrics snapshot missing collector {want!r}")


def validate_tree(spans: list) -> None:
    by_id = {}
    for doc in spans:
        if doc["span_id"] in by_id:
            fail(f"duplicate span_id {doc['span_id']}")
        by_id[doc["span_id"]] = doc
    for doc in spans:
        parent = doc.get("parent_id")
        if parent is not None and parent not in by_id:
            fail(f"orphan parent: span {doc['name']}/{doc['span_id']} "
                 f"references missing parent {parent}")
        # cycle check: walk to the root (bounded by span count)
        seen = set()
        cur = doc
        while cur is not None:
            if cur["span_id"] in seen:
                fail(f"span ancestry cycle at {cur['span_id']}")
            seen.add(cur["span_id"])
            cur = by_id.get(cur.get("parent_id"))


def validate_request_trace(spans: list, poison_id: str,
                           clean_ids: list) -> dict:
    by_id = {d["span_id"]: d for d in spans}
    submits = [d for d in spans if d["name"] == "serve.submit"]
    flushes = [d for d in spans if d["name"] == "serve.flush"]
    isolations = [d for d in spans if d["name"] == "serve.isolation"]
    all_ids = set(clean_ids) | {poison_id}

    submit_ids = {d.get("request_id") for d in submits}
    if not all_ids <= submit_ids:
        fail(f"submit spans missing request ids: {all_ids - submit_ids}")

    # the cohort's flush span must carry every member's id and parent
    # under a submit span (the cross-thread handoff)
    cohort_flushes = [d for d in flushes
                      if all_ids <= set(d.get("attrs", {})
                                        .get("request_ids", []))]
    if not cohort_flushes:
        fail("no serve.flush span carries the full cohort's request ids")
    fl = cohort_flushes[0]
    parent = by_id.get(fl.get("parent_id"))
    if parent is None or parent["name"] != "serve.submit":
        fail("flush span does not parent under a serve.submit span")
    if fl["status"] != "error":
        fail("poisoned cohort's flush span not marked error")

    # every isolation retry span: nests under the flush tree and its
    # request_ids are a subset of the cohort — and the poison id appears
    # on the capacity-1 isolation span that failed
    poison_leaf = None
    for iso in isolations:
        rids = set(iso.get("attrs", {}).get("request_ids", []))
        if not rids <= all_ids:
            fail(f"isolation span carries foreign request ids: {rids}")
        anc = iso
        while anc is not None and anc["name"] != "serve.flush":
            anc = by_id.get(anc.get("parent_id"))
        if anc is None:
            fail("isolation span not rooted under a serve.flush span")
        if rids == {poison_id} and iso["status"] == "error":
            poison_leaf = iso
    if not isolations:
        fail("no serve.isolation spans under an injected flush fault")
    if poison_leaf is None:
        fail("no failed capacity-1 isolation span pinned to the poison "
             "request id")
    return {"submits": len(submits), "flushes": len(flushes),
            "isolations": len(isolations)}


def validate_prometheus() -> None:
    text = telemetry.prometheus_text()
    for needle in ("skylark_engine_lifetime_misses",
                   "skylark_serve_submitted",
                   "skylark_serve_flush_failures",
                   "skylark_resilience_faults_fired_total",
                   "skylark_telemetry_spans_total"):
        if needle not in text:
            fail(f"prometheus_text missing {needle}")


def main() -> None:
    poison_id, clean_ids = run_workload()
    spans = load_lines("spans-*.jsonl")
    metric_lines = load_lines("metrics-*.jsonl")
    if not spans:
        fail("no spans exported")
    validate_schema(spans, metric_lines)
    validate_tree(spans)
    counts = validate_request_trace(spans, poison_id, clean_ids)
    validate_prometheus()
    print(json.dumps({
        "metric": "telemetry_smoke", "ok": True, "spans": len(spans),
        "metric_lines": len(metric_lines), **counts,
        "poison_request": poison_id,
    }))


if __name__ == "__main__":
    main()
