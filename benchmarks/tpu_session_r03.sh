#!/bin/bash
# One-shot on-chip evidence session for round 3. Ordered by priority so a
# mid-session tunnel wedge still leaves the most valuable artifacts
# committed. Each step is bounded; artifacts land in benchmarks/.
#
# bench.py prints exactly one JSON line on stdout (its status chatter goes
# to stderr), so each measurement captures stdout straight to a file —
# piping through the run() wrapper would interleave its own echoes and
# lose the record (that bug ate the first headline capture of the round).
#
# Usage: bash benchmarks/tpu_session_r03.sh
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y-%m-%dT%H:%M:%SZ)
echo "# TPU session $STAMP"

run() {  # run <timeout_s> <label> <cmd...>
    local t=$1 label=$2; shift 2
    echo "== $label"
    timeout "$t" "$@"
    local rc=$?
    echo "== $label rc=$rc"
    return $rc
}

bench_to() {  # bench_to <timeout_s> <label> <outfile> [env pairs...]
    local t=$1 label=$2 out=$3; shift 3
    echo "== $label"
    timeout "$t" env "$@" python bench.py > "$out" 2>/tmp/bench_"$label".err
    local rc=$?
    echo "== $label rc=$rc $(tail -c 400 "$out")"
    if [ $rc -ne 0 ]; then
        echo "== $label stderr: $(tail -c 400 /tmp/bench_"$label".err)"
    fi
    return $rc
}

# save_rec <infile> <outfile> [extra-json-fields] — parse the last
# non-empty line of <infile> as the bench record, stamp capture time.
# Single-file mode refuses value=null so a wedged rerun never overwrites
# a good capture; JSONL append mode keeps null rows (they document the
# failure and cannot destroy prior rows).
save_rec() {
    python - "$@" <<'EOF'
import datetime, json, sys
inp, out = sys.argv[1], sys.argv[2]
extras = json.loads(sys.argv[3]) if len(sys.argv) > 3 else None
lines = [l for l in open(inp) if l.strip()]
if not lines:
    sys.exit(f"save_rec: {inp} is empty; not touching {out}")
rec = json.loads(lines[-1])
stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
if extras is None:
    if rec.get("value") is None:
        sys.exit(f"save_rec: {inp} has value=null ({rec.get('error')}); not touching {out}")
    rec["provenance"] = {"captured": stamp, "by": "benchmarks/tpu_session_r03.sh"}
    json.dump(rec, open(out, "w"), indent=1)
else:
    with open(out, "a") as f:
        f.write(json.dumps({**extras, "captured": stamp, "rec": rec}) + "\n")
EOF
}

# 0. liveness (cheap)
run 90 probe python bench.py --probe || exit 1

# 1. on-chip oracle tests at the CURRENT defaults (bf16x3) — re-certify
#    (5 tests: rowwise f32/bf16x3, columnwise, fused-RFT epilogue,
#    pipelined; each may cold-compile). SKYLARK_SKIP_ORACLE=1 resumes a
#    session whose oracle step already passed and is committed.
if [ "${SKYLARK_SKIP_ORACLE:-0}" != "1" ]; then
run 900 oracle env SKYLARK_TEST_TPU=1 python -m pytest tests/test_pallas_dense.py -m tpu -rA \
    2>&1 | tail -10 | tee -a benchmarks/tpu_validation_r03.txt
fi

# 2. headline measurement (default m-tile, all three regimes measured by
#    the child) — the driver-compatible JSON line, saved with provenance
bench_to 480 headline /tmp/headline_r03.json SKYLARK_BENCH_DEADLINE=420 && \
    save_rec /tmp/headline_r03.json benchmarks/results_tpu_r03_headline.json

# 3. m-tile sweep on the headline config (pick the best, record all).
#    Generation is re-paid per m-tile sweep, so larger tiles cut the
#    dominant VPU cost; 1024 may exceed the VMEM plan (then _qualify
#    shrinks it — the record shows which tile actually ran).
for MT in 256 512 1024; do
    bench_to 420 "mtile-$MT" /tmp/mtile_$MT.json \
        SKYLARK_PALLAS_MTILE=$MT SKYLARK_BENCH_DEADLINE=360 && \
    save_rec /tmp/mtile_$MT.json benchmarks/results_tpu_r03_mtile_sweep.jsonl \
        "{\"m_tile\": $MT}"
done

# 3b. generation-pipelining A/B at the best expected tile
bench_to 420 pipeline /tmp/pipeline_512.json \
    SKYLARK_PALLAS_PIPELINE=1 SKYLARK_PALLAS_MTILE=512 SKYLARK_BENCH_DEADLINE=360 && \
    save_rec /tmp/pipeline_512.json benchmarks/results_tpu_r03_mtile_sweep.jsonl \
        '{"pipeline": 1, "m_tile": 512}'

# 4. full bench suite at full scale on chip (all BASELINE configs + FRFT)
run 1800 run_all python benchmarks/run_all.py --scale full --save 3 \
    2>&1 | tee benchmarks/results_tpu_r03_runall.log | tail -8

# 5. north-star rehearsal: large rand-SVD + accuracy gates
run 900 svd_scale python benchmarks/svd_scale.py --mode chip --save

echo "# session done $(date -u +%Y-%m-%dT%H:%M:%SZ)"
