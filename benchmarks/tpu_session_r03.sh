#!/bin/bash
# One-shot on-chip evidence session for round 3. Ordered by priority so a
# mid-session tunnel wedge still leaves the most valuable artifacts
# committed. Each step is bounded; artifacts land in benchmarks/.
#
# Usage: bash benchmarks/tpu_session_r03.sh
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y-%m-%dT%H:%M:%SZ)
echo "# TPU session $STAMP"

run() {  # run <timeout_s> <label> <cmd...>
    local t=$1 label=$2; shift 2
    echo "== $label"
    timeout "$t" "$@"
    local rc=$?
    echo "== $label rc=$rc"
    return $rc
}

# 0. liveness (cheap)
run 90 probe python bench.py --probe || exit 1

# 1. on-chip oracle tests at the CURRENT defaults (bf16x3) — re-certify
#    (5 tests: rowwise f32/bf16x3, columnwise, fused-RFT epilogue,
#    pipelined; each may cold-compile)
run 900 oracle env SKYLARK_TEST_TPU=1 python -m pytest tests/test_pallas_dense.py -m tpu -rA \
    2>&1 | tail -10 | tee -a benchmarks/tpu_validation_r03.txt

# 2. headline measurement (default m-tile, all three regimes measured by
#    the child) — the driver-compatible JSON line, saved with provenance
run 480 headline python bench.py 2>&1 | tail -1 | tee /tmp/headline_r03.json
python - <<'EOF'
import json, datetime
rec = json.load(open("/tmp/headline_r03.json"))
rec["provenance"] = {"captured": datetime.datetime.now(datetime.timezone.utc).isoformat(),
                     "by": "benchmarks/tpu_session_r03.sh"}
json.dump(rec, open("benchmarks/results_tpu_r03_headline.json", "w"), indent=1)
EOF

# 3. m-tile sweep on the headline config (pick the best, record all).
#    Generation is re-paid per m-tile sweep, so larger tiles cut the
#    dominant VPU cost; 1024 may exceed the VMEM plan (then _qualify
#    shrinks it — the record shows which tile actually ran).
for MT in 256 512 1024; do
    run 420 "mtile-$MT" env SKYLARK_PALLAS_MTILE=$MT SKYLARK_BENCH_DEADLINE=360 \
        python bench.py 2>&1 | tail -1 | \
        sed "s/^/{\"m_tile\": $MT, \"rec\": /; s/\$/}/" \
        >> benchmarks/results_tpu_r03_mtile_sweep.jsonl
done

# 3b. generation-pipelining A/B at the best expected tile
run 420 pipeline env SKYLARK_PALLAS_PIPELINE=1 SKYLARK_PALLAS_MTILE=512 \
    SKYLARK_BENCH_DEADLINE=360 python bench.py 2>&1 | tail -1 | \
    sed 's/^/{"pipeline": 1, "m_tile": 512, "rec": /; s/$/}/' \
    >> benchmarks/results_tpu_r03_mtile_sweep.jsonl

# 4. full bench suite at full scale on chip (all BASELINE configs + FRFT)
run 1800 run_all python benchmarks/run_all.py --scale full --save 3 \
    2>&1 | tee benchmarks/results_tpu_r03_runall.log | tail -8

# 5. north-star rehearsal: large rand-SVD + accuracy gates
run 900 svd_scale python benchmarks/svd_scale.py --mode chip --save

echo "# session done $(date -u +%Y-%m-%dT%H:%M:%SZ)"
