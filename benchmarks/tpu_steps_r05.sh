# Round-5 harvest steps. SOURCED by tpu_watch_r05.sh on every loop
# cycle, so edits here take effect on the next probe without restarting
# the watcher. Defines: SWEEP_SPECS, have_* predicates, attempt_all,
# all_done. The watcher provides: log, probe_ok, give_up, note_fail,
# FAILS, commit_artifacts.
#
# Window budget order (VERDICT.md r4 "Next round" #1):
#   0. on-chip oracle re-certification — HARD GATE before any number
#   1. cross-layer on-chip battery (tests/test_tpu_battery.py): its
#      test_jlt_xla_path_vs_host_gemm is the dense/eager-dispatch oracle
#      covering the r4-changed XLA paths (dense.py veto, frft/fut layout)
#   2. m-tile x pipelined-generation A/B sweep (the >=100 GB/s hunt);
#      each row records its cold-process wall_s
#   3. headline capture with extras -> results_tpu_r05_headline.json
#   4. run_all full suite, resumable -> results_r05_tpu.json (includes
#      the FRFT-vs-RFT on-chip config, VERDICT #4)
#   5. 32k^2 rand-SVD north-star chip mode (VERDICT #5)

# m_tile  pipeline  precision — the r5 sweep adds the 2-pass
# "bf16gen2" regime (operator defined as the bf16 rounding of the
# stream; pass-count ceiling 216 GB/s vs bf16x3's 144 — VERDICT #3's
# "2-pass compensated split" lever, oracle-tested in
# test_pallas_dense.py::test_bf16gen2_regime_matches_rounded_operator_oracle)
SWEEP_SPECS=("512 1 bf16x3" "512 0 bf16x3" "512 1 bf16gen2"
             "512 0 bf16gen2" "1024 1 bf16x3" "1024 0 bf16x3"
             "1024 1 bf16gen2" "256 0 bf16x3")

have_oracle_recert() { [ -f benchmarks/.tpu_oracle_recert_r05 ]; }
have_battery() { [ -f benchmarks/.tpu_battery_r05 ]; }
have_fastfood_cert() { [ -f benchmarks/.tpu_fastfood_r05 ]; }
have_headline() {
    python - <<'EOF'
import json, sys
try:
    rec = json.load(open("benchmarks/results_tpu_r05_headline.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if rec.get("value") is not None else 1)
EOF
}

have_sweep_point() {  # have_sweep_point <m_tile> <pipeline 0|1> <precision>
    python - "$1" "$2" "${3:-bf16x3}" <<'EOF'
import json, sys
mt, pipe, prec = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
try:
    rows = [json.loads(l)
            for l in open("benchmarks/results_tpu_r05_mtile_sweep.jsonl")
            if l.strip()]
except FileNotFoundError:
    sys.exit(1)
ok = any(r.get("m_tile") == mt and int(r.get("pipeline", 0)) == pipe
         and r.get("precision", "bf16x3") == prec
         and (r.get("rec") or {}).get("value") is not None for r in rows)
sys.exit(0 if ok else 1)
EOF
}

have_runall() {
    python - <<'EOF'
import ast, json, sys
# expected metric set derived from run_all.py's DIRECTIONS literal (ast,
# not import — importing would pay jax startup per probe cycle)
need = None
for node in ast.walk(ast.parse(open("benchmarks/run_all.py").read())):
    if (isinstance(node, ast.Assign)
            and getattr(node.targets[0], "id", None) == "DIRECTIONS"):
        need = set(ast.literal_eval(node.value))
if not need:
    sys.exit(1)
try:
    doc = json.load(open("benchmarks/results_r05_tpu.json"))
except Exception:
    sys.exit(1)
if doc.get("scale") != "full":
    sys.exit(1)
done = {r["metric"] for r in doc["results"] if r.get("value") is not None}
sys.exit(0 if need <= done else 1)
EOF
}

runall_count() {
    python - <<'EOF'
import json
try:
    recs = json.load(open("benchmarks/results_r05_tpu.json"))["results"]
    print(sum(1 for r in recs if r.get("value") is not None))
except Exception:
    print(0)
EOF
}

have_svd_chip() {
    python - <<'EOF'
import json, sys
try:
    recs = json.load(open("benchmarks/results_svd_scale_r05.json"))
except Exception:
    sys.exit(1)
ok = any(r.get("mode") == "chip" and r.get("backend") != "cpu"
         and r.get("value") is not None
         and r.get("accuracy_gate") == "pass" for r in recs)
sys.exit(0 if ok else 1)
EOF
}

# ---- steps ----------------------------------------------------------------

sweep_point() {  # sweep_point <m_tile> <pipeline 0|1> <precision>
    local mt=$1 pipe=$2 prec=${3:-bf16x3} t0 wall
    local out=/tmp/sweep_r05_${1}_${2}_${prec}.json
    log "sweep m_tile=$mt pipeline=$pipe precision=$prec"
    t0=$(date +%s)
    timeout 360 env JAX_PLATFORMS=tpu SKYLARK_PALLAS_MTILE=$mt \
        SKYLARK_PALLAS_PIPELINE=$pipe SKYLARK_BENCH_PRECISION=$prec \
        SKYLARK_BENCH_DEADLINE=300 SKYLARK_BENCH_SKIP_EXTRAS=1 \
        python bench.py > "$out" 2>/tmp/sweep_r05_err.log
    wall=$(( $(date +%s) - t0 ))
    python - "$out" "$mt" "$pipe" "$prec" "$wall" <<'EOF'
import datetime, json, sys
out, mt, pipe, prec, wall = sys.argv[1], int(sys.argv[2]), \
    int(sys.argv[3]), sys.argv[4], int(sys.argv[5])
lines = [l for l in open(out) if l.strip()]
if not lines:
    sys.exit(1)
rec = json.loads(lines[-1])
if rec.get("value") is None:
    print("  -> null:", (rec.get("error") or "")[:160])
    sys.exit(1)
row = {"m_tile": mt, "pipeline": pipe, "precision": prec, "wall_s": wall,
       "captured": datetime.datetime.now(datetime.timezone.utc).isoformat(),
       "rec": rec}
with open("benchmarks/results_tpu_r05_mtile_sweep.jsonl", "a") as f:
    f.write(json.dumps(row) + "\n")
print("  -> captured", rec["value"], "GB/s in", wall, "s cold")
EOF
}

headline_step() {
    local out=/tmp/headline_r05.json t0 wall
    t0=$(date +%s)
    timeout 480 env JAX_PLATFORMS=tpu SKYLARK_BENCH_DEADLINE=420 \
        python bench.py > "$out" 2>/tmp/headline_r05.err
    wall=$(( $(date +%s) - t0 ))
    python - "$out" "$wall" <<'EOF'
import datetime, glob, json, re, sys
out, wall = sys.argv[1], int(sys.argv[2])
lines = [l for l in open(out) if l.strip()]
if not lines:
    sys.exit("headline: empty output")
rec = json.loads(lines[-1])
if rec.get("value") is None:
    sys.exit("headline: value=null: %s" % (rec.get("error") or "")[:200])
# vs_baseline vs the best PRIOR round's committed on-chip headline
# (VERDICT r3 weak #5: the r03 record said 1.0 while the r02 prior was
# 32.3 — the driver-format record must carry the cross-round ratio)
prior = None
for p in glob.glob("benchmarks/results_tpu_r*_headline.json"):
    m = re.search(r"_r(\d+)_", p)
    if not m or int(m.group(1)) >= 5:
        continue
    try:
        v = json.load(open(p)).get("value")
    except Exception:
        continue
    if v is not None and (prior is None or int(m.group(1)) > prior[0]):
        prior = (int(m.group(1)), v)
if prior:
    rec["vs_baseline"] = round(rec["value"] / prior[1], 4)
    rec["baseline_prior_round"] = {"round": prior[0], "GBps": prior[1]}
rec["cold_start_wall_s"] = wall
rec["provenance"] = {
    "captured": datetime.datetime.now(datetime.timezone.utc).isoformat(),
    "by": "benchmarks/tpu_steps_r05.sh headline_step"}
json.dump(rec, open("benchmarks/results_tpu_r05_headline.json", "w"),
          indent=1)
print("  -> headline", rec["value"], "GB/s, cold wall", wall, "s")
EOF
}

attempt_all() {
    local failed=0
    # step 0: HARD GATE — no certification stamp, no captures this pass
    if ! have_oracle_recert; then
        give_up oracle && return 1
        log "on-chip oracle re-certification"
        timeout 900 env JAX_PLATFORMS=tpu SKYLARK_TEST_TPU=1 \
            python -m pytest tests/test_pallas_dense.py -m tpu -rA -q \
            > /tmp/oracle_recert_r05.log 2>&1
        local rc=$?
        {
            echo "# r05 oracle re-certification $(date -u +%Y-%m-%dT%H:%M:%SZ) rc=$rc"
            tail -10 /tmp/oracle_recert_r05.log
        } >> benchmarks/tpu_validation_r05.txt
        if [ $rc -eq 0 ]; then
            # stamp carries the certified kernel CLOSURE's content hash
            # (pallas_dense + params + randgen; `bench.py --stamp` is
            # the single source of the format) so bench.py's
            # oracle_fresh survives git checkouts (no mtimes) and a
            # post-certification knob/stream change can't ride it
            echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $(python bench.py --stamp)" \
                > benchmarks/.tpu_oracle_recert_r05
            commit_artifacts "r05 on-chip oracle re-certification"
        else
            [ $rc -eq 5 ] && log "oracle recert selected no tests (rc=5)"
            note_fail oracle
            return 1
        fi
    fi
    if [ -f tests/test_tpu_battery.py ] && ! have_battery \
            && ! give_up battery; then
        log "cross-layer on-chip battery"
        timeout 1200 env JAX_PLATFORMS=tpu SKYLARK_TEST_TPU=1 \
            python -m pytest tests/test_tpu_battery.py -m tpu -rA -q \
            > /tmp/tpu_battery_r05.log 2>&1
        local rc=$?
        {
            echo "# r05 cross-layer battery $(date -u +%Y-%m-%dT%H:%M:%SZ) rc=$rc"
            tail -25 /tmp/tpu_battery_r05.log
        } >> benchmarks/tpu_validation_r05.txt
        if [ $rc -eq 0 ]; then
            date -u +%Y-%m-%dT%H:%M:%SZ > benchmarks/.tpu_battery_r05
            commit_artifacts "r05 cross-layer on-chip battery passed"
        else
            failed=1
            note_fail battery || return 1
        fi
    fi
    for spec in "${SWEEP_SPECS[@]}"; do
        set -- $spec
        if ! have_sweep_point "$1" "$2" "$3" \
                && ! give_up "sweep_$1_$2_$3"; then
            if sweep_point "$1" "$2" "$3"; then
                commit_artifacts "r05 sweep point m_tile=$1 pipeline=$2 precision=$3"
            else
                failed=1
                note_fail "sweep_$1_$2_$3" || return 1
            fi
        fi
    done
    if ! have_headline && ! give_up headline; then
        log "headline capture (defaults + extras)"
        if headline_step; then
            commit_artifacts "r05 on-chip headline capture"
        else
            failed=1
            note_fail headline || return 1
        fi
    fi
    if ! have_runall && ! give_up runall; then
        log "run_all --scale full --save 5 --resume"
        local n0
        n0=$(runall_count)
        timeout 2400 env JAX_PLATFORMS=tpu python benchmarks/run_all.py \
            --scale full --save 5 --resume 2>&1 | tail -12
        if have_runall; then
            commit_artifacts "r05 on-chip run_all complete"
        else
            failed=1
            if [ "$(runall_count)" -gt "$n0" ]; then
                log "run_all partial progress ($n0 -> $(runall_count))"
                commit_artifacts "r05 on-chip run_all partial ($(runall_count) configs)"
                probe_ok || return 1
            else
                note_fail runall || return 1
            fi
        fi
    fi
    if ! have_svd_chip && ! give_up svd; then
        log "svd_scale --mode chip"
        timeout 900 env JAX_PLATFORMS=tpu \
            python benchmarks/svd_scale.py --mode chip --save --round 5 \
            2>&1 | tail -3
        if have_svd_chip; then
            commit_artifacts "r05 north-star chip-mode rand-SVD captured"
        else
            failed=1
            note_fail svd || return 1
        fi
    fi
    # fused Fastfood kernel: first-ever Mosaic compile of the
    # take_along_axis lane gather + on-chip oracle (interpret-mode
    # semantics already pinned on CPU). A compile failure is itself
    # round evidence — the log tail lands in tpu_validation_r05.txt
    # either way, and run_all's frft config captures the timing A/B.
    if [ -f tests/test_pallas_fastfood.py ] && ! have_fastfood_cert \
            && ! give_up fastfood; then
        log "fused Fastfood kernel on-chip certification"
        timeout 900 env JAX_PLATFORMS=tpu SKYLARK_TEST_TPU=1 \
            python -m pytest tests/test_pallas_fastfood.py -m tpu -rA -q \
            > /tmp/tpu_fastfood_r05.log 2>&1
        local rc=$?
        {
            echo "# r05 fused-fastfood cert $(date -u +%Y-%m-%dT%H:%M:%SZ) rc=$rc"
            tail -25 /tmp/tpu_fastfood_r05.log
        } >> benchmarks/tpu_validation_r05.txt
        if [ $rc -eq 0 ]; then
            date -u +%Y-%m-%dT%H:%M:%SZ > benchmarks/.tpu_fastfood_r05
            commit_artifacts "r05 fused Fastfood kernel certified on chip"
        else
            failed=1
            commit_artifacts "r05 fused Fastfood compile/oracle transcript (rc=$rc)"
            note_fail fastfood || return 1
        fi
    fi
    return $failed
}

all_done() {
    have_oracle_recert || return 1
    for spec in "${SWEEP_SPECS[@]}"; do
        set -- $spec
        have_sweep_point "$1" "$2" "$3" || return 1
    done
    have_headline || return 1
    have_runall || return 1
    if [ -f tests/test_tpu_battery.py ]; then
        have_battery || return 1
    fi
    have_svd_chip || return 1
    if [ -f tests/test_pallas_fastfood.py ]; then
        have_fastfood_cert || return 1
    fi
    return 0
}
