#!/bin/bash
# Tunnel-window harvester for the round-3 on-chip evidence package.
#
# The r2/r3 tunnel pattern is short live windows (minutes) between
# multi-hour wedges. This watcher probes cheaply on a loop; the moment a
# probe answers it runs the REMAINING evidence steps in value-per-second
# order. Every step is idempotent — it checks its own artifact before
# running — so the watcher survives any number of wedge/recover cycles
# and a re-launch never repeats completed work.
#
# Steps (priority order; artifacts under benchmarks/):
#   1. m-tile sweep points + pipelined-generation A/B on the headline
#      config (results_tpu_r03_mtile_sweep.jsonl) — the ≥100 GB/s hunt
#   2. full bench suite, all BASELINE configs, incremental + resumable
#      (results_r03_tpu.json via run_all.py --resume)
#   3. 32k² rand-SVD north-star rehearsal (results_svd_scale_r03.json)
#
# Usage: setsid nohup bash benchmarks/tpu_watch_r03.sh \
#            > /tmp/tpu_watch_r03.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
END=$(( $(date +%s) + ${SKYLARK_WATCH_HOURS:-10} * 3600 ))

log() { echo "[$(date -u +%H:%M:%S)] $*"; }

# Every backend touch pins JAX_PLATFORMS=tpu: on a wedge between probe
# and step, JAX would otherwise fall back to CPU — burning the window on
# a chip-sized problem and saving misleading backend=cpu records. Pinned,
# a wedged step fails fast instead. The probe also requires the literal
# "PROBE_OK tpu" (a CPU-fallback PROBE_OK must not count as live).
probe_ok() {
    timeout 100 env JAX_PLATFORMS=tpu python bench.py --probe 2>/dev/null \
        | grep -q "PROBE_OK tpu"
}

# ---- step predicates: 0 = already captured -------------------------------

have_sweep_point() {  # have_sweep_point <m_tile> <pipeline 0|1>
    python - "$1" "$2" <<'EOF'
import json, sys
mt, pipe = int(sys.argv[1]), int(sys.argv[2])
try:
    rows = [json.loads(l)
            for l in open("benchmarks/results_tpu_r03_mtile_sweep.jsonl")
            if l.strip()]
except FileNotFoundError:
    sys.exit(1)
ok = any(r.get("m_tile") == mt and int(r.get("pipeline", 0)) == pipe
         and (r.get("rec") or {}).get("value") is not None for r in rows)
sys.exit(0 if ok else 1)
EOF
}

have_runall() {
    python - <<'EOF'
import ast, json, sys
# expected metric set derived from run_all.py's DIRECTIONS literal (ast,
# not import — importing would pay jax startup per probe cycle), so a
# bench added or removed there can't silently break done-detection
need = None
for node in ast.walk(ast.parse(open("benchmarks/run_all.py").read())):
    if (isinstance(node, ast.Assign)
            and getattr(node.targets[0], "id", None) == "DIRECTIONS"):
        need = set(ast.literal_eval(node.value))
if not need:
    sys.exit(1)
try:
    doc = json.load(open("benchmarks/results_r03_tpu.json"))
except Exception:
    sys.exit(1)
if doc.get("scale") != "full":
    # a small-scale spot-check file must not satisfy full-scale
    # done-detection (scale is not in the filename, unlike backend)
    sys.exit(1)
done = {r["metric"] for r in doc["results"]
        if r.get("value") is not None}
sys.exit(0 if need <= done else 1)
EOF
}

runall_count() {  # captured (non-null) configs — progress detection
    python - <<'EOF'
import json
try:
    recs = json.load(open("benchmarks/results_r03_tpu.json"))["results"]
    print(sum(1 for r in recs if r.get("value") is not None))
except Exception:
    print(0)
EOF
}

have_svd_chip() {
    python - <<'EOF'
import json, sys
try:
    recs = json.load(open("benchmarks/results_svd_scale_r03.json"))
except Exception:
    sys.exit(1)
# gate must have PASSED: a FAILing run writes a record too, and shipping
# it as "captured" would end the watch with a failing north-star record
ok = any(r.get("mode") == "chip" and r.get("backend") != "cpu"
         and r.get("value") is not None
         and r.get("accuracy_gate") == "pass" for r in recs)
sys.exit(0 if ok else 1)
EOF
}

# ---- steps ----------------------------------------------------------------

sweep_point() {  # sweep_point <m_tile> <pipeline 0|1>
    local mt=$1 pipe=$2 out=/tmp/sweep_${1}_${2}.json
    log "sweep m_tile=$mt pipeline=$pipe"
    # pipeline env passed unconditionally ("0" means disabled), so no
    # empty-array expansion exists to trip `set -u` on older bash
    timeout 360 env JAX_PLATFORMS=tpu SKYLARK_PALLAS_MTILE=$mt \
        SKYLARK_PALLAS_PIPELINE=$pipe \
        SKYLARK_BENCH_DEADLINE=300 SKYLARK_BENCH_SKIP_EXTRAS=1 \
        python bench.py > "$out" 2>/tmp/sweep_err.log
    python - "$out" "$mt" "$pipe" <<'EOF'
import datetime, json, sys
out, mt, pipe = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
lines = [l for l in open(out) if l.strip()]
if not lines:
    sys.exit(1)
rec = json.loads(lines[-1])
if rec.get("value") is None:
    print("  -> null:", (rec.get("error") or "")[:160])
    sys.exit(1)
row = {"m_tile": mt, "pipeline": pipe,
       "captured": datetime.datetime.now(datetime.timezone.utc).isoformat(),
       "rec": rec}
with open("benchmarks/results_tpu_r03_mtile_sweep.jsonl", "a") as f:
    f.write(json.dumps(row) + "\n")
print("  -> captured", rec["value"], "GB/s")
EOF
}

# One watcher pass: attempt every remaining step while the tunnel lives.
# After a step fails, a quick re-probe discriminates wedge from
# deterministic failure: wedged → return to cheap probing (don't burn the
# remaining steps' timeouts); still live → keep going so one persistently
# failing step can't starve the steps after it (e.g. a crashing run_all
# config must not block the svd rehearsal for the whole watch).
# Deterministic-failure cap: a step that fails twice while the tunnel is
# LIVE (probe passes right after the failure) is given up for this
# watcher process — a hopeless config at the head of the list must not
# burn every few-minute live window for the whole watch. Wedge failures
# (probe fails after the step) don't count toward the cap.
declare -A FAILS

give_up() { [ "${FAILS[$1]:-0}" -ge 2 ]; }

note_fail() {  # note_fail <step-key> → rc 1 on wedge (stop this pass)
    if probe_ok; then
        FAILS[$1]=$(( ${FAILS[$1]:-0} + 1 ))
        if give_up "$1"; then
            log "step $1 failed ${FAILS[$1]}x live — giving up on it"
        fi
        return 0
    fi
    return 1
}

# m_tile/pipeline sweep points, priority order — single list shared by
# attempt_all and all_done (drift between two copies would either stall
# the watch or end it early)
SWEEP_SPECS=("1024 0" "1024 1" "512 1" "512 0" "256 0")

have_oracle_recert() {
    [ -f benchmarks/.tpu_oracle_recert_r03 ]
}

attempt_all() {
    local failed=0
    # step 0: re-certify the on-chip oracle battery at the CURRENT code
    # (the kernel plumbing was refactored after the last certification;
    # measurements taken on a silently-broken kernel would mislabel the
    # XLA fallback as kernel numbers)
    if ! have_oracle_recert; then
        # HARD GATE, not just a priority: measurements taken on an
        # uncertified kernel would permanently capture XLA-fallback
        # numbers labeled as kernel performance (have_* predicates never
        # re-measure). No certification stamp → no captures this pass,
        # and a given-up recert means the watch captures nothing.
        give_up oracle && return 1
        log "on-chip oracle re-certification"
        timeout 900 env JAX_PLATFORMS=tpu SKYLARK_TEST_TPU=1 \
            python -m pytest tests/test_pallas_dense.py -m tpu -rA -q \
            > /tmp/oracle_recert.log 2>&1
        local rc=$?
        {
            echo "# re-certification $(date -u +%Y-%m-%dT%H:%M:%SZ) rc=$rc"
            tail -10 /tmp/oracle_recert.log
        } >> benchmarks/tpu_validation_r03.txt
        if [ $rc -eq 0 ]; then   # pytest 0 = every selected test passed
            date -u +%Y-%m-%dT%H:%M:%SZ > benchmarks/.tpu_oracle_recert_r03
        else
            # rc=5 means ZERO tests were selected (the -m tpu battery
            # didn't even run — a conftest/gating problem, not a kernel
            # failure); either way nothing was certified, so no stamp.
            [ $rc -eq 5 ] && log "oracle recert selected no tests (rc=5)"
            note_fail oracle
            return 1
        fi
    fi
    for spec in "${SWEEP_SPECS[@]}"; do
        set -- $spec
        if ! have_sweep_point "$1" "$2" && ! give_up "sweep_$1_$2"; then
            if ! sweep_point "$1" "$2"; then
                failed=1
                note_fail "sweep_$1_$2" || return 1
            fi
        fi
    done
    if ! have_runall && ! give_up runall; then
        log "run_all --scale full --save 3 --resume"
        local n0
        n0=$(runall_count)
        timeout 2400 env JAX_PLATFORMS=tpu python benchmarks/run_all.py \
            --scale full --save 3 --resume 2>&1 | tail -12
        if ! have_runall; then
            failed=1
            if [ "$(runall_count)" -gt "$n0" ]; then
                # incremental progress: a timeout mid-suite is the suite
                # being long, not a deterministic failure — the resume
                # pass converges across windows, so don't strike it
                log "run_all partial progress ($n0 -> $(runall_count))"
                probe_ok || return 1
            else
                note_fail runall || return 1
            fi
        fi
    fi
    if ! have_svd_chip && ! give_up svd; then
        log "svd_scale --mode chip"
        timeout 900 env JAX_PLATFORMS=tpu \
            python benchmarks/svd_scale.py --mode chip --save \
            2>&1 | tail -3
        if ! have_svd_chip; then
            failed=1
            note_fail svd || return 1
        fi
    fi
    return $failed
}

all_done() {
    have_oracle_recert || return 1
    for spec in "${SWEEP_SPECS[@]}"; do
        set -- $spec
        have_sweep_point "$1" "$2" || return 1
    done
    have_runall && have_svd_chip
}

log "watch start (deadline $(date -u -d @$END +%H:%M:%S))"
while [ "$(date +%s)" -lt "$END" ]; do
    if all_done; then
        log "ALL STEPS CAPTURED — exiting"
        exit 0
    fi
    if probe_ok; then
        log "tunnel LIVE — attempting remaining steps"
        if attempt_all; then
            if all_done; then
                log "ALL STEPS CAPTURED — exiting"
                exit 0
            fi
            # exit code distinguishes an incomplete package from success
            # (0 = all captured, 2 = deadline, 3 = steps given up)
            log "remaining steps given up after repeated live" \
                "failures — exiting"
            exit 3
        fi
        log "step failed — back to probing"
    else
        log "wedged"
    fi
    sleep 150
done
log "deadline reached with steps remaining"
exit 2
