#!/bin/bash
# Round-4 tunnel-window harvester. Probes cheaply on a loop; the moment a
# probe answers, runs the remaining evidence steps (tpu_steps_r05.sh) in
# value-per-second order. The steps file is SOURCED each cycle so steps
# can be added/edited while the watcher runs — no kill/relaunch needed.
#
# Every step is idempotent (artifact-existence predicates) and every
# capture is git-committed immediately (the r3 lesson: a wedge can
# orphan anything uncommitted).
#
# Usage: setsid nohup bash benchmarks/tpu_watch_r05.sh \
#            > /tmp/tpu_watch_r05.log 2>&1 & echo $! > /tmp/tpu_watch_r05.pid
set -u
cd "$(dirname "$0")/.."
END=$(( $(date +%s) + ${SKYLARK_WATCH_HOURS:-12} * 3600 ))

log() { echo "[$(date -u +%H:%M:%S)] $*"; }

# Every backend touch pins JAX_PLATFORMS=tpu (a CPU-fallback PROBE_OK
# must not count as live; a wedged step fails fast instead of silently
# measuring CPU).
probe_ok() {
    timeout 100 env JAX_PLATFORMS=tpu python bench.py --probe 2>/dev/null \
        | grep -q "PROBE_OK tpu"
}

# Deterministic-failure strikes: a step that fails twice while the tunnel
# is LIVE (probe passes right after the failure) is given up for this
# watcher process. Wedge failures don't count.
declare -A FAILS

give_up() { [ "${FAILS[$1]:-0}" -ge 2 ]; }

note_fail() {  # note_fail <step-key> -> rc 1 on wedge (stop this pass)
    if probe_ok; then
        FAILS[$1]=$(( ${FAILS[$1]:-0} + 1 ))
        if give_up "$1"; then
            log "step $1 failed ${FAILS[$1]}x live — giving up on it"
        fi
        return 0
    fi
    return 1
}

# Commit ONLY benchmarks/ paths (pathspec commit: concurrent interactive
# staging elsewhere in the tree must not be swept into watcher commits).
commit_artifacts() {
    git add -A benchmarks/ 2>/dev/null
    git commit -q -m "$1" -- benchmarks/ 2>/dev/null || true
}

log "r05 watch start (deadline $(date -u -d @$END +%H:%M:%S))"
while [ "$(date +%s)" -lt "$END" ]; do
    # re-read the step definitions each cycle (live-editable)
    if ! source benchmarks/tpu_steps_r05.sh; then
        log "steps file failed to source — retrying next cycle"
        sleep 60
        continue
    fi
    if all_done; then
        log "ALL STEPS CAPTURED — exiting"
        exit 0
    fi
    if probe_ok; then
        log "tunnel LIVE — attempting remaining steps"
        t0=$(date +%s)
        attempt_all
        rc=$?
        log "attempt_all rc=$rc after $(( $(date +%s) - t0 ))s"
        if [ $rc -eq 0 ] && all_done; then
            log "ALL STEPS CAPTURED — exiting"
            exit 0
        fi
    else
        log "wedged"
    fi
    sleep 150
done
log "deadline reached with steps remaining"
exit 2
