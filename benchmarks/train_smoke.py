"""Train smoke — the CI training-jobs chaos gate (docs/training).

Proves the training-as-a-service contract over REAL process replicas:
two tenants each train a kernel-ridge model via sliced Block-ADMM on a
2-replica fleet while an interactive sketch storm runs through the
same front door. One replica — the owner of tenant A's job, pinned by
session-ring probing — boots with a seeded ``SKYLARK_FAULT_PLAN``
carrying a ``train.slice`` **crash** spec: a hard ``os._exit`` fired
on its third slice attempt, BEFORE that slice's journaled append (the
deterministic ``kill -9`` mid-slice). The pool reaps the corpse, the
router's resume chain adopts the on-disk session on the surviving
peer — fencing the dead owner's lease — and the job replays exactly
the acked two-slice prefix and continues.

Asserts:

- **bit-equal resume**: both tenants' trained coefficients are
  bit-equal to an uninterrupted single-process reference run of the
  same engine with the same slice boundaries — the SIGKILL is
  invisible in the bits;
- **zero client-visible failures**: both job futures resolve with
  results (no error), and every interactive request in the storm
  succeeds within its bounded retries;
- the pool reaped exactly the victim (``crashed_names()``) and the
  router counted at least one train resume dispatch;
- **interactive p99 within gate**: best_effort training slices drain
  only in idle scheduler slots, so the storm's p99 stays under
  ``P99_GATE_S`` even with two jobs training and a replica dying.

Prints one JSON record; exits nonzero on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

HYPER = {"num_features": 16, "num_partitions": 2, "lam": 1e-2,
         "seed": 3, "tol": 1e-3}
BUDGET_ITERS = 200
SLICE_ITERS = 2
P99_GATE_S = 1.0
STORM_ROWS, STORM_D, STORM_S = 32, 8, 8

# fires on the victim's THIRD slice attempt, before that slice's
# append is journaled — the acked prefix the peer must replay is
# exactly two slices
CRASH_PLAN = json.dumps({"seed": 7, "faults": [
    {"site": "train.slice", "crash": True, "on_hit": 3}]})


def _krr_ops(seed, m=48, d=6):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((m, d))
    Y = (X[:, :1] > 0).astype(np.float64) * 2 - 1
    return {"X": X, "Y": Y}


def _reference(ops):
    """The uninterrupted run: the same engine, the same slice
    boundaries, one process, no chaos. The sliced job is bit-equal to
    this by the tentpole invariant (tests/test_train.py proves it at
    every boundary); the smoke proves it survives a SIGKILL."""
    from libskylark_tpu.train import make_engine

    eng = make_engine("admm_krr", dict(HYPER), ops)
    st = eng.init()
    it = 0
    while it < BUDGET_ITERS:
        st = eng.step(st, min(SLICE_ITERS, BUDGET_ITERS - it))
        it += SLICE_ITERS
        if eng.info(st)["converged"]:
            break
    return eng.result(st)


def _pick_sid(router, prefix, owner):
    """A session id whose ring preference puts ``owner`` first — the
    same deterministic construction ``submit_train_job`` dispatches
    by, probed without recording an assignment."""
    for i in range(256):
        sid = f"{prefix}{i}"
        if router._session_candidates(sid)[0] == owner:
            return sid
    raise RuntimeError(f"no session id maps to {owner!r}")


def _storm(router, stop, rec):
    """The interactive foreground: one sketch stream at
    ``qos_class="interactive"`` with bounded same-request retries;
    latency is client-perceived (retries included)."""
    from libskylark_tpu import Context
    from libskylark_tpu import sketch as sk

    T = sk.JLT(STORM_ROWS, STORM_S, Context(seed=1))
    rng = np.random.default_rng(5)
    ops = [rng.standard_normal((STORM_ROWS, STORM_D)).astype(np.float32)
           for _ in range(4)]
    # warm both replicas' executable caches before the clock starts
    for A in ops + ops:
        router.submit_sketch(T, A, qos_class="interactive").result(
            timeout=60.0)
    lat, retries, failures, i = [], 0, 0, 0
    while not stop.is_set():
        A = ops[i % len(ops)]
        t0 = time.perf_counter()
        for _attempt in range(4):
            try:
                router.submit_sketch(
                    T, A, qos_class="interactive").result(timeout=30.0)
                lat.append(time.perf_counter() - t0)
                break
            except Exception:  # noqa: BLE001 — retry through the kill
                retries += 1
                time.sleep(0.05)
        else:
            failures += 1
        i += 1
        time.sleep(0.005)
    rec["latencies"] = lat
    rec["retries"] = retries
    rec["client_visible_failures"] = failures


def main() -> int:
    import atexit
    import shutil

    from libskylark_tpu import fleet
    from libskylark_tpu.train import TrainJobSpec

    scratch = tempfile.mkdtemp(prefix="skylark_train_smoke_")
    os.environ["SKYLARK_SESSION_DIR"] = scratch
    atexit.register(shutil.rmtree, scratch, ignore_errors=True)

    ops_a, ops_b = _krr_ops(13), _krr_ops(29)
    ref_a, ref_b = _reference(ops_a), _reference(ops_b)
    violations = []

    def victim_env(name):
        # the crash spec rides into ONE child only — the chaos plan
        # must not leak into the surviving peer
        return ({"SKYLARK_FAULT_PLAN": CRASH_PLAN}
                if name == "r0" else None)

    pool = fleet.ReplicaPool(2, backend="process", max_batch=4,
                             replica_env=victim_env)
    router = fleet.Router(pool)
    storm_rec: dict = {}
    stop = threading.Event()
    try:
        # pin tenant A's job onto the victim and tenant B's onto the
        # peer, so the crash deterministically lands in A's third
        # slice while B trains undisturbed
        sid_a = _pick_sid(router, "train-krr-a", "r0")
        sid_b = _pick_sid(router, "train-krr-b", "r1")
        storm = threading.Thread(
            target=_storm, args=(router, stop, storm_rec), daemon=True)
        storm.start()
        fut_a = router.submit_train_job(
            TrainJobSpec(solver="admm_krr", hyper=dict(HYPER),
                         budget_iters=BUDGET_ITERS,
                         slice_iters=SLICE_ITERS,
                         tenant="tenant-a").to_dict(),
            operands=ops_a, session_id=sid_a)
        fut_b = router.submit_train_job(
            TrainJobSpec(solver="admm_krr", hyper=dict(HYPER),
                         budget_iters=BUDGET_ITERS,
                         slice_iters=SLICE_ITERS,
                         tenant="tenant-b").to_dict(),
            operands=ops_b, session_id=sid_b)
        job_failures = 0
        outs = {}
        for tenant, fut in (("a", fut_a), ("b", fut_b)):
            try:
                outs[tenant] = fut.result(timeout=240.0)
            except Exception as e:  # noqa: BLE001 — gate accounting
                job_failures += 1
                violations.append(
                    f"tenant {tenant}: job future failed: {e!r}")
        stop.set()
        storm.join(timeout=120.0)
        rstats = router.stats()
        crashed = pool.crashed_names()
        survivor = pool.get("r1").stats().get("train") or {}
    finally:
        stop.set()
        router.close()
        pool.shutdown()

    for tenant, ref in (("a", ref_a), ("b", ref_b)):
        out = outs.get(tenant)
        if out is None:
            continue
        if not out.get("converged"):
            violations.append(f"tenant {tenant}: job did not converge")
        if not np.array_equal(out["coef"], ref["coef"]):
            violations.append(
                f"tenant {tenant}: coefficients not bit-equal to the "
                "uninterrupted reference run")
        if out["iterations"] != ref["iterations"]:
            violations.append(
                f"tenant {tenant}: {out['iterations']} iterations, "
                f"reference ran {ref['iterations']}")
    if crashed != ["r0"]:
        violations.append(
            f"pool reaped {crashed}, expected ['r0'] (the "
            "train.slice crash-fault victim)")
    if rstats["train_resumes"] < 1:
        violations.append(
            "router counted no train resume — the kill never forced "
            "a handoff")
    if survivor.get("resumes", 0) < 1:
        violations.append(
            "surviving replica reports no manager resume — the "
            "session was not adopted from disk")
    storm_failures = storm_rec.get("client_visible_failures", 0)
    if storm_failures or job_failures:
        violations.append(
            f"client-visible failures: {storm_failures} storm, "
            f"{job_failures} job")
    lat = storm_rec.get("latencies") or []
    p99 = float(np.percentile(lat, 99)) if lat else None
    if not lat:
        violations.append("storm recorded no latencies — inert")
    elif p99 > P99_GATE_S:
        violations.append(
            f"interactive p99 {p99 * 1e3:.1f} ms over the "
            f"{P99_GATE_S * 1e3:.0f} ms gate — training slices "
            "starved the interactive class")

    rec = {
        "metric": "train_smoke",
        "budget_iters": BUDGET_ITERS,
        "slice_iters": SLICE_ITERS,
        "iterations": {t: outs[t]["iterations"] for t in outs},
        "crashed": crashed,
        "train_jobs": rstats["train_jobs"],
        "train_resumes": rstats["train_resumes"],
        "survivor_train": survivor,
        "storm_requests": len(lat),
        "storm_retries": storm_rec.get("retries", 0),
        "interactive_p99_ms": None if p99 is None else p99 * 1e3,
        "p99_gate_ms": P99_GATE_S * 1e3,
        "violations": violations,
    }
    print(json.dumps(rec), flush=True)
    if violations:
        print("train smoke FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
