# Sphinx configuration for the libskylark_tpu documentation site
# (the analog of the reference's doc/sphinx tree). Build with:
#   sphinx-build -b html docs docs/_build
# The axon dev image ships no sphinx; CI environments that have it can
# add the build to script/ci.
import os
import sys

sys.path.insert(0, os.path.abspath(".."))

project = "libskylark_tpu"
author = "libskylark_tpu developers"
release = "0.4"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.mathjax",
    "sphinx.ext.viewcode",
]

autodoc_mock_imports = ["jax", "jaxlib", "orbax", "scipy", "h5py"]
exclude_patterns = ["_build"]
html_theme = "alabaster"
