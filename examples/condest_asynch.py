"""Condition estimation + randomized block solvers.

Runnable port of ref: examples/condest.cpp and examples/asynch.cpp — LSQR-
based condition estimation of a tall matrix, then solving a sparse SPD
system with the randomized block Gauss-Seidel / flexible-CG pair that
replaces the reference's asynchronous OpenMP solvers (SURVEY §2.9 P8).
"""

import numpy as np
import jax.numpy as jnp

from libskylark_tpu import Context
from libskylark_tpu.algorithms.asynch import (
    rand_block_fcg,
    rand_block_gauss_seidel,
)
from libskylark_tpu.nla.condest import condest


def main():
    rng = np.random.default_rng(9)

    # -- condition estimation (ref: examples/condest.cpp)
    m, n = 4000, 60
    U, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    svals = np.geomspace(1.0, 1e-3, n)
    A = jnp.asarray((U * svals) @ V.T, jnp.float32)
    est = condest(A, Context(seed=13))
    est = est[0] if isinstance(est, tuple) else est
    print(f"condest: estimated {float(est):.3g}, "
          f"true {svals[0] / svals[-1]:.3g}")

    # -- randomized block solvers on sparse SPD (ref: examples/asynch.cpp)
    N = 400
    import scipy.sparse as sp

    G = sp.random(N, N, density=0.02, random_state=3, dtype=np.float64)
    A_spd = (G @ G.T + 10 * sp.eye(N)).tocsc()
    Ad = jnp.asarray(A_spd.toarray(), jnp.float32)
    x_true = rng.standard_normal(N).astype(np.float32)
    b = jnp.asarray(A_spd @ x_true, jnp.float32)

    for name, fn in (("rand-block-GS", rand_block_gauss_seidel),
                     ("rand-block-FCG", rand_block_fcg)):
        out = fn(Ad, b, Context(seed=17))
        x = out[0] if isinstance(out, tuple) else out
        rel = float(np.linalg.norm(np.asarray(x).ravel() - x_true)
                    / np.linalg.norm(x_true))
        print(f"{name}: rel err {rel:.2e}")


if __name__ == "__main__":
    main()
