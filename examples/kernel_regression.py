"""Kernel ridge regression across compute regimes + an ADMM kernel machine.

Runnable port of ref: examples/kernel_regression.cpp — train the same
Gaussian-kernel classifier with (a) exact KRR, (b) random-features KRR,
(c) the faster-KRR CG solver with random-features preconditioner, and
(d) a Block-ADMM kernel machine, comparing accuracy.
"""

import numpy as np
import jax.numpy as jnp

from libskylark_tpu import Context, ml
from libskylark_tpu.algorithms.prox import HingeLoss, L2Regularizer
from libskylark_tpu.ml import krr
from libskylark_tpu.ml.admm import BlockADMMSolver


def main():
    rng = np.random.default_rng(7)
    n, d = 600, 10
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (np.sin(X[:, 0]) + 0.5 * X[:, 1] > 0).astype(np.int64)
    Xtr, ytr, Xte, yte = X[:400], y[:400], X[400:], y[400:]

    ctx = Context(seed=11)
    kernel = ml.Gaussian(d, sigma=2.0)
    Ytr = jnp.asarray(2.0 * ytr - 1.0, jnp.float32)

    def accuracy(dv):
        pred = (np.asarray(dv).reshape(-1) > 0).astype(np.int64)
        return 100.0 * (pred == yte).mean()

    # (a) exact KRR
    alpha = krr.kernel_ridge(kernel, jnp.asarray(Xtr), Ytr, 0.01)
    dv = kernel.gram(jnp.asarray(Xte), jnp.asarray(Xtr)) @ alpha
    print(f"KernelRidge (exact):    {accuracy(dv):.1f} %")

    # (b) random-features KRR
    fmap, w = krr.approximate_kernel_ridge(
        kernel, jnp.asarray(Xtr), Ytr, 0.01, s=512, context=ctx)
    from libskylark_tpu.sketch import ROWWISE

    dv = fmap.apply(jnp.asarray(Xte), ROWWISE) @ w
    print(f"ApproximateKernelRidge: {accuracy(dv):.1f} %")

    # (c) CG with random-features preconditioner
    alpha = krr.faster_kernel_ridge(
        kernel, jnp.asarray(Xtr), Ytr, 0.01, s=256, context=ctx)
    dv = kernel.gram(jnp.asarray(Xte), jnp.asarray(Xtr)) @ alpha
    print(f"FasterKernelRidge (CG): {accuracy(dv):.1f} %")

    # (d) Block-ADMM kernel machine (hinge loss)
    solver = BlockADMMSolver.from_kernel(
        ctx, HingeLoss(), L2Regularizer(), 0.01, 512, kernel,
        num_partitions=4)
    solver.maxiter = 20
    model = solver.train(Xtr, ytr)
    labels, _ = model.predict(jnp.asarray(Xte))
    acc = 100.0 * (np.asarray(labels) == yte).mean()
    print(f"BlockADMM (hinge):      {acc:.1f} %")


if __name__ == "__main__":
    main()
