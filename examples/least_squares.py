"""Least squares three ways: exact, sketch-and-solve, Blendenpik.

Runnable port of ref: examples/least_squares.cpp + regression.cpp —
compare solution quality and residuals of the exact solver, the
sketch-and-solve quick estimate, and the sketch-preconditioned accurate
solver on a tall synthetic problem.
"""

import jax.numpy as jnp
import numpy as np

from libskylark_tpu import Context, nla


def main():
    m, n = 20_000, 100
    rng = np.random.default_rng(1)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    x_true = jnp.asarray(rng.standard_normal(n), jnp.float32)
    b = A @ x_true + 0.1 * jnp.asarray(rng.standard_normal(m), jnp.float32)

    ctx = Context(seed=2)

    x_exact = jnp.linalg.lstsq(A, b)[0]

    x_sketch = nla.approximate_least_squares(A, b, ctx)
    x_fast = nla.fast_least_squares(A, b, ctx)
    if isinstance(x_fast, tuple):
        x_fast = x_fast[0]

    def report(name, x):
        x = jnp.asarray(x).reshape(-1)
        res = float(jnp.linalg.norm(A @ x - b))
        err = float(jnp.linalg.norm(x - x_exact.reshape(-1))
                    / jnp.linalg.norm(x_exact))
        print(f"{name:>16}: residual {res:10.4f}   "
              f"rel err vs exact {err:.2e}")

    report("exact", x_exact)
    report("sketch-and-solve", x_sketch)
    report("Blendenpik", x_fast)


if __name__ == "__main__":
    main()
