"""Preemption-resilient training — checkpoint/resume for long solver runs.

The reference restarts a killed run from zero (its §5 aux-subsystem
survey has no checkpoint row; models serialize, solver state does not —
ref: ml/skylark_ml.cpp:15-172 holds everything in process memory). On
TPU, long solves on preemptible capacity are the norm, so this framework
persists LIVE solver state: the ADMM consensus carry and the streaming
sketch accumulators survive a SIGKILL and resume bit-identical to an
uninterrupted run.

This example simulates three preemptions:

1. A Block-ADMM training run "dies" after 4 of 12 iterations; a second
   invocation over the same checkpoint directory resumes at iteration 5
   and finishes — coefficients equal the never-interrupted run exactly.
2. A streaming ingestion+sketch job dies mid-stream; the rerun
   fast-forwards past the rows already folded in (re-reading but not
   re-sketching them) and completes to the same sketch.
3. A REAL ``SIGTERM`` (the TPU/GCE eviction protocol) arrives with the
   resilience handler installed: the live microbatch serving executor
   drains (every queued future resolves; new submits are load-shed),
   and the training loop notices the preemption flag at its next
   iteration boundary, cuts a final synchronous checkpoint, and stops —
   the rerun resumes from it and finishes bit-identical to the
   uninterrupted run.
"""

import os
import signal
import tempfile

import numpy as np

from libskylark_tpu import Context, engine, resilience
from libskylark_tpu import sketch as sk
from libskylark_tpu.algorithms.prox import L2Regularizer, SquaredLoss
from libskylark_tpu.io.streaming import StreamingCWT
from libskylark_tpu.ml.admm import BlockADMMSolver


def _solver(maxiter: int) -> BlockADMMSolver:
    s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01,
                        num_features=16, num_partitions=2)
    s.maxiter = maxiter
    s.tol = 0.0
    return s


def main() -> None:
    rng = np.random.default_rng(0)
    X = rng.standard_normal((200, 16)).astype(np.float32)
    Y = np.sin(X[:, 0]).astype(np.float32)

    # -- 1. ADMM: preempted at iteration 4, resumed to 12 ----------------
    ref = _solver(12).train(X, Y, regression=True)

    with tempfile.TemporaryDirectory() as ck:
        # "preempted": the process reached only iteration 4 before dying
        # (maxiter=4 stands in for the kill; a real SIGKILL behaves the
        # same — orbax commits steps atomically, in-flight saves vanish)
        _solver(4).train(X, Y, regression=True,
                         checkpoint=ck, checkpoint_every=2)
        # rerun of the FULL job over the same directory: resumes at 5
        resumed = _solver(12).train(X, Y, regression=True,
                                    checkpoint=ck, checkpoint_every=2)

    drift = np.abs(np.asarray(resumed.coef) - np.asarray(ref.coef)).max()
    print(f"ADMM resume vs uninterrupted: max |diff| = {drift}")
    assert drift == 0.0, "resume must be bit-identical"

    # -- 2. streaming sketch: preempted mid-stream -----------------------
    n, d, s_dim, bs = 512, 8, 64, 64
    Xs = rng.standard_normal((n, d)).astype(np.float32)
    Ys = rng.standard_normal(n).astype(np.float32)

    def batches(upto: int):
        for i in range(0, upto, bs):
            yield Xs[i:i + bs], Ys[i:i + bs]

    one_shot, _ = StreamingCWT(n, s_dim, Context(seed=3)).sketch(
        batches(n))

    with tempfile.TemporaryDirectory() as ck:
        # ingestion job dies after 4 of 8 batches
        StreamingCWT(n, s_dim, Context(seed=3)).sketch(
            batches(n // 2), checkpoint=ck, checkpoint_every=1)
        # rerun: fast-forwards 256 rows, sketches the rest
        SX, _ = StreamingCWT(n, s_dim, Context(seed=3)).sketch(
            batches(n), checkpoint=ck, checkpoint_every=1)

    drift = np.abs(np.asarray(SX) - np.asarray(one_shot)).max()
    print(f"streaming resume vs one-shot sketch: max |diff| = {drift}")
    assert drift == 0.0, "streamed resume must equal the one-shot sketch"

    # -- 3. a real SIGTERM: serve drain + final checkpoint + resume ------
    resilience.install_preemption_handler()
    try:
        # a live serving executor with queued (un-flushed) requests...
        ex = engine.MicrobatchExecutor(max_batch=8, linger_us=10_000_000)
        T = sk.CWT(16, 8, Context(seed=7))
        futs = [ex.submit_sketch(
            T, rng.standard_normal((16, 2)).astype(np.float32))
            for _ in range(5)]

        # ...when the scheduler preempts us. CPython delivers the signal
        # at the next bytecode boundary in the main thread: the handler
        # sets the sticky preemption flag and kicks off the teardown
        # (executor drain + checkpoint hooks) on its own thread — never
        # blocking the interrupted frame, which may hold the very locks
        # the drain needs.
        os.kill(os.getpid(), signal.SIGTERM)
        assert resilience.wait_for_preemption_teardown(timeout=60.0)

        with tempfile.TemporaryDirectory() as ck:
            # the training loop polls the flag at each iteration
            # boundary: it stops after iteration 1 and cuts a final
            # checkpoint before returning
            _solver(12).train(X, Y, regression=True,
                              checkpoint=ck, checkpoint_every=0)
            assert all(f.done() for f in futs), "drain left orphans"
            assert ex.state == engine.STOPPED
            print(f"SIGTERM: executor drained ({len(futs)} futures "
                  f"resolved), training stopped at a checkpointed "
                  f"iteration boundary")

            # the replacement process clears the flag and resumes
            resilience.reset_preemption()
            resumed = _solver(12).train(X, Y, regression=True,
                                        checkpoint=ck, checkpoint_every=0)
        drift = np.abs(np.asarray(resumed.coef)
                       - np.asarray(ref.coef)).max()
        print(f"SIGTERM resume vs uninterrupted: max |diff| = {drift}")
        assert drift == 0.0, "SIGTERM resume must be bit-identical"
    finally:
        resilience.uninstall_preemption_handler()

    print("preemptible training: all three resume paths bit-identical")


if __name__ == "__main__":
    main()
