"""Random feature maps: how well Z·Zᵀ approximates the kernel gram.

Runnable port of ref: examples/random_features.cpp — build regular, fast
(Fastfood) and quasi (leaped Halton) feature maps for a Gaussian kernel
and measure ‖Z·Zᵀ − K‖/‖K‖ as the feature count grows.
"""

import jax.numpy as jnp
import numpy as np

from libskylark_tpu import Context
from libskylark_tpu import sketch as sk
from libskylark_tpu.ml.kernels import Gaussian


def main():
    n, d = 512, 32
    sigma = 3.0
    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)

    kernel = Gaussian(d, sigma=sigma)
    K = kernel.gram(X)
    nK = float(jnp.linalg.norm(K))

    for tag in ("regular", "fast", "quasi"):
        line = [f"{tag:>8}:"]
        for s in (256, 1024, 4096):
            Z = kernel.create_rft(s, Context(seed=5), tag).apply(
                X, sk.ROWWISE)
            err = float(jnp.linalg.norm(Z @ Z.T - K)) / nK
            line.append(f"s={s}: {err:.4f}")
        print("  ".join(line))


if __name__ == "__main__":
    main()
