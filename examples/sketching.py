"""Sketching 101 — apply dense and hash transforms, locally and sharded.

Runnable port of ref: examples/elemental.cpp (create a matrix, sketch it
with JLT/CWT/FJLT both columnwise and rowwise). Works on any backend; on a
multi-device host the sharded apply demonstrates the layout-independence
oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu import Context
from libskylark_tpu import sketch as sk


def main():
    n, m, s = 10_000, 64, 512
    ctx = Context(seed=38734)
    A = jnp.asarray(
        np.random.default_rng(0).standard_normal((n, m)), jnp.float32)

    for name, T in [
        ("JLT", sk.JLT(n, s, ctx)),
        ("CWT", sk.CWT(n, s, ctx)),
        ("FJLT", sk.FJLT(n, s, ctx)),
    ]:
        SA = T.apply(A, sk.COLUMNWISE)            # (s, m)
        # norms are approximately preserved (the JL property)
        ratio = float(jnp.linalg.norm(SA) / jnp.linalg.norm(A))
        print(f"{name}: S·A {SA.shape}, ‖SA‖/‖A‖ = {ratio:.3f}")

    # sharded apply == local apply at the same (seed, counter)
    devs = jax.devices()
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(devs), ("rows",))
        T = sk.JLT(n, s, ctx)
        local = T.apply(A, sk.COLUMNWISE)
        A_sh = jax.device_put(A, NamedSharding(mesh, P("rows", None)))
        sharded = T.apply(A_sh, sk.COLUMNWISE)
        diff = float(jnp.abs(local - sharded).max())
        print(f"sharded-vs-local oracle ({len(devs)} devices): "
              f"max diff {diff:.2e}")


if __name__ == "__main__":
    main()
