"""Streaming ingestion — bounded-memory datasets into sharded device memory.

Runnable port of the reference's oversized-dataset story (the HDFS line
streamer + chunked root-reads-and-scatters readers,
ref: utility/hdfs.hpp:11, utility/io/libsvm_io.hpp:812-1876,
ml/io.hpp:256-507): a libsvm dataset flows batch-by-batch into a
row-sharded device array (peak host memory one batch + one shard), the
same reader runs off ANY line transport (here: a local WebHDFS REST stub
standing in for a real namenode — the exact protocol of
io/webhdfs.webhdfs_lines), and a streaming CWT sketch of the file equals
the one-shot sketch of the whole matrix (counter-stream order
independence).
"""

import http.server
import os
import tempfile
import threading

import jax.numpy as jnp
import numpy as np

import libskylark_tpu.io as skio
from libskylark_tpu import Context
from libskylark_tpu import parallel as par
from libskylark_tpu import sketch as sk


def _write_dataset(path: str, n: int = 600, d: int = 24) -> None:
    rng = np.random.default_rng(7)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    with open(path, "w") as fh:
        for i in range(n):
            feats = " ".join(f"{j + 1}:{X[i, j]:.6f}" for j in range(d))
            fh.write(f"{y[i]} {feats}\n")


class _WebHDFSStub:
    """Minimal WebHDFS endpoint: OPEN answers with the namenode→datanode
    307 redirect, then streams the bytes — io/webhdfs.py speaks to a real
    namenode identically."""

    def __init__(self, body: bytes):
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.startswith("/webhdfs"):
                    self.send_response(307)
                    self.send_header(
                        "Location", f"http://127.0.0.1:{stub.port}/data")
                    self.end_headers()
                else:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def main():
    path = os.path.join(tempfile.mkdtemp(), "train.libsvm")
    _write_dataset(path)
    mesh = par.make_mesh()

    # 1. bounded-memory read, straight into a row-sharded device array
    X, Y = skio.read_libsvm_sharded(path, mesh, batch_rows=64)
    print(f"sharded read: X {X.shape} on {len(X.sharding.device_set)} "
          f"device(s)")

    # 2. the same reader off the WebHDFS transport (REST protocol)
    with open(path, "rb") as fh:
        stub = _WebHDFSStub(fh.read())
    try:
        url = f"http://127.0.0.1:{stub.port}"
        dims = skio.scan_libsvm_dims(skio.webhdfs_lines(url, "/train"))
        Xh, _ = skio.read_libsvm_sharded(
            skio.webhdfs_lines(url, "/train"), mesh, batch_rows=64,
            dims=dims)
    finally:
        stub.close()
    diff = float(jnp.abs(X - Xh).max())
    print(f"webhdfs transport read == local read: max diff {diff:.1e}")

    # 3. streaming sketch == one-shot sketch (order-independent streams)
    s = 48
    ctx_seed = 91
    SX, SY = skio.stream_sketch_libsvm(path, s, Context(seed=ctx_seed),
                                       batch_rows=64)
    T = sk.CWT(X.shape[0], s, Context(seed=ctx_seed))
    want = T.apply(X, sk.COLUMNWISE)
    diff = float(jnp.abs(SX - want).max())
    print(f"streaming sketch == one-shot sketch: max diff {diff:.1e} "
          f"({X.shape[0]} rows → {s})")


if __name__ == "__main__":
    main()
