"""libskylark_tpu — a TPU-native randomized numerical linear algebra framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of libSkylark
(/root/reference): sketching transforms, sketch-accelerated NLA (randomized
SVD, sketched least squares, condition estimation) and ML on top of sketching
(kernel ridge regression, RLSC, block-ADMM kernel machines, graph spectral
embedding, local community detection).

Design stance (see SURVEY.md §7): sharding specs over a `jax.sharding.Mesh`
replace Elemental's distribution template parameters; XLA collectives over
ICI/DCN replace Boost.MPI; `jax.random`'s counter-based Threefry replaces
Random123 — preserving the reference's core determinism property that a
sketch's entries are a pure function of (seed, counter), independent of the
data layout (ref: base/randgen.hpp:98-115, base/context.hpp:19-194).
"""

__version__ = "0.1.0"

# NOTE on platform selection: the package deliberately does NOT touch
# ``jax_platforms`` at import. On images whose sitecustomize pre-imports
# jax with a pinned platform, honoring ``JAX_PLATFORMS`` here would
# equally clobber a script's deliberate post-import
# ``jax.config.update("jax_platforms", ...)`` (the ambient environment
# may export the pinned platform globally, making "the user set the env
# var" undetectable). The CLI entry points — applications, not library
# code — honor the env var instead (cli.honor_platform_env), and library
# scripts use the documented post-import config update (the
# tests/conftest.py pattern).

from libskylark_tpu.base.precision import install_default_matmul_precision

# f32 matmuls must actually be f32 on TPU (default lowering is one bf16
# MXU pass — outside the 1e-4 oracle; see base/precision.py for the
# measurement). Env opt-out: SKYLARK_MATMUL_PRECISION=default.
install_default_matmul_precision()

from libskylark_tpu.base.context import Context
from libskylark_tpu.base import errors
from libskylark_tpu.base.sparse import SparseMatrix
from libskylark_tpu.base.dist_sparse import DistSparseMatrix, distribute_sparse
from libskylark_tpu import telemetry

__all__ = [
    "Context", "errors", "telemetry", "__version__",
    "SparseMatrix", "DistSparseMatrix", "distribute_sparse",
]
