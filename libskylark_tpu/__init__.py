"""libskylark_tpu — a TPU-native randomized numerical linear algebra framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of libSkylark
(/root/reference): sketching transforms, sketch-accelerated NLA (randomized
SVD, sketched least squares, condition estimation) and ML on top of sketching
(kernel ridge regression, RLSC, block-ADMM kernel machines, graph spectral
embedding, local community detection).

Design stance (see SURVEY.md §7): sharding specs over a `jax.sharding.Mesh`
replace Elemental's distribution template parameters; XLA collectives over
ICI/DCN replace Boost.MPI; `jax.random`'s counter-based Threefry replaces
Random123 — preserving the reference's core determinism property that a
sketch's entries are a pure function of (seed, counter), independent of the
data layout (ref: base/randgen.hpp:98-115, base/context.hpp:19-194).
"""

__version__ = "0.1.0"


def _honor_platform_env() -> None:
    """Make an explicit ``JAX_PLATFORMS`` request effective even where a
    ``sitecustomize`` pre-imported jax with another platform pinned (the
    axon image does; the env var is only read at first jax import, so a
    user's ``JAX_PLATFORMS=cpu skylark_ml ...`` would otherwise silently
    target — and hang on — a wedged TPU tunnel). Same post-import update
    the test conftest and benchmarks use; no-op when unset."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass  # never block import over a platform hint


_honor_platform_env()

from libskylark_tpu.base.precision import install_default_matmul_precision

# f32 matmuls must actually be f32 on TPU (default lowering is one bf16
# MXU pass — outside the 1e-4 oracle; see base/precision.py for the
# measurement). Env opt-out: SKYLARK_MATMUL_PRECISION=default.
install_default_matmul_precision()

from libskylark_tpu.base.context import Context
from libskylark_tpu.base import errors
from libskylark_tpu.base.sparse import SparseMatrix
from libskylark_tpu.base.dist_sparse import DistSparseMatrix, distribute_sparse

__all__ = [
    "Context", "errors", "__version__",
    "SparseMatrix", "DistSparseMatrix", "distribute_sparse",
]
