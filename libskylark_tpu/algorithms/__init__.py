"""Algorithms layer: Krylov solvers, prox operators, regression framework
(SURVEY.md §2.3)."""

from libskylark_tpu.algorithms import asynch, krylov, precond, prox, regression
from libskylark_tpu.algorithms.krylov import KrylovParams, cg, chebyshev, flexible_cg, lsqr
from libskylark_tpu.algorithms.precond import (
    FunctionPrecond,
    IdPrecond,
    MatPrecond,
    Precond,
    TriInversePrecond,
)
from libskylark_tpu.algorithms.regression import (
    AcceleratedParams,
    RegressionProblem,
    solve_l2_accelerated,
    solve_l2_exact,
    solve_l2_sketched,
)

__all__ = [
    "asynch",
    "krylov",
    "precond",
    "prox",
    "regression",
    "KrylovParams",
    "cg",
    "chebyshev",
    "flexible_cg",
    "lsqr",
    "Precond",
    "IdPrecond",
    "MatPrecond",
    "TriInversePrecond",
    "FunctionPrecond",
    "RegressionProblem",
    "AcceleratedParams",
    "solve_l2_exact",
    "solve_l2_sketched",
    "solve_l2_accelerated",
]
