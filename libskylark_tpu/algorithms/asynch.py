"""Randomized block solvers — the synchronous TPU analog of AsyRGS/AsyFCG.

The reference's asynchronous solvers (ref: algorithms/asynch/AsyRGS.hpp:82,
AsyFCG.hpp:8) exploit lock-free shared-memory updates (`#pragma omp atomic`)
— a CPU-threading idiom with no TPU analog (SURVEY.md §2.9 P8 documents this
divergence). The mathematical content — randomized (block) Gauss-Seidel
sweeps on an SPD system, usable standalone or as a flexible-CG inner
preconditioner — is preserved in a deterministic, jittable form: block order
is drawn per sweep from a context key (replayable), and the sweep is a
`lax.scan` over sequential block updates, each block solved exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import jax.random as jr
from jax import lax

from libskylark_tpu.algorithms import krylov
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params


@dataclasses.dataclass
class RandBlockParams(Params):
    """ref: algorithms/asynch/asy_iter_params.hpp:8-40 (sweeps_lim ~ sweeps
    between convergence checks; syncs_lim ~ outer checks)."""

    block_size: int = 64
    sweeps: int = 4
    tolerance: float = 1e-6
    max_outer: int = 20


class _BlockSystem:
    """SPD system padded to uniform blocks (identity on padded rows), with a
    single randomized-sweep primitive shared by the GS and FCG entry points."""

    def __init__(self, A: jnp.ndarray, block_size: int):
        A = jnp.asarray(A)
        n = A.shape[0]
        bs = min(block_size, n)
        nblocks = -(-n // bs)
        pad = nblocks * bs - n
        if pad:
            A_p = jnp.zeros((n + pad, n + pad), A.dtype)
            A_p = (
                A_p.at[:n, :n].set(A)
                .at[jnp.arange(n, n + pad), jnp.arange(n, n + pad)].set(1.0)
            )
        else:
            A_p = A
        self.A_p = A_p
        self.n, self.bs, self.nblocks, self.pad = n, bs, nblocks, pad
        self.block_rows = jnp.arange(nblocks) * bs

    def pad_cols(self, X: jnp.ndarray) -> jnp.ndarray:
        if not self.pad:
            return X
        return jnp.concatenate(
            [X, jnp.zeros((self.pad, X.shape[1]), X.dtype)], axis=0
        )

    def sweep(self, X: jnp.ndarray, B_p: jnp.ndarray, skey) -> jnp.ndarray:
        """One randomized block Gauss-Seidel sweep over the padded system."""
        order = jr.permutation(skey, self.nblocks)
        A_p, bs = self.A_p, self.bs

        def update(X, bidx):
            rows = self.block_rows[bidx] + jnp.arange(bs)
            A_J = A_p[rows, :]
            A_JJ = A_p[rows[:, None], rows[None, :]]
            resid = B_p[rows, :] - A_J @ X + A_JJ @ X[rows, :]
            x_J = jnp.linalg.solve(A_JJ, resid)
            return X.at[rows, :].set(x_J), None

        X, _ = lax.scan(update, X, order)
        return X


def rand_block_gauss_seidel(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    params: Optional[RandBlockParams] = None,
    X0: Optional[jnp.ndarray] = None,
):
    """Randomized block Gauss-Seidel on SPD A (AsyRGS analog).

    Per sweep: visit the blocks in a fresh random order; for each block J,
    solve A[J,J]·x_J = b_J − A[J,:]·x + A[J,J]·x_J exactly. Returns
    (X, sweeps_done).
    """
    params = params or RandBlockParams()
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    n, k = B.shape
    sys = _BlockSystem(A, params.block_size)
    key = context.allocate().key

    B_p = sys.pad_cols(B)
    X = sys.pad_cols(
        jnp.zeros((n, k), B.dtype) if X0 is None else jnp.asarray(X0).reshape(n, k)
    )
    nrm_b = jnp.maximum(jnp.linalg.norm(B_p), jnp.finfo(B.dtype).eps)

    sweeps_done = 0
    for _outer in range(params.max_outer):
        for _s in range(params.sweeps):
            X = sys.sweep(X, B_p, jr.fold_in(key, sweeps_done))
            sweeps_done += 1
        res = jnp.linalg.norm(B_p - sys.A_p @ X) / nrm_b
        if float(res) <= params.tolerance:
            break

    X = X[:n, :]
    return (X[:, 0] if squeeze else X), sweeps_done


def rand_block_fcg(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    params: Optional[RandBlockParams] = None,
    krylov_params: Optional[krylov.KrylovParams] = None,
):
    """Flexible CG with one randomized block Gauss-Seidel sweep as the
    (varying) inner preconditioner — the AsyFCG analog
    (ref: algorithms/asynch/AsyFCG.hpp:8). The padded system is built once;
    inside the flexible-CG trace it is a loop-invariant constant."""
    params = params or RandBlockParams()
    A = jnp.asarray(A)
    sys = _BlockSystem(A, params.block_size)
    key = context.allocate().key
    n = sys.n

    def apply_gs(R, it):
        Rp = sys.pad_cols(R)
        Z = jnp.zeros_like(Rp)
        Z = sys.sweep(Z, Rp, jr.fold_in(key, it))
        return Z[:n, :]

    return krylov.flexible_cg(A, B, params=krylov_params, precond=apply_gs)
