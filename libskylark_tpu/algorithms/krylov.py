"""Krylov solvers: LSQR, CG, FlexibleCG, Chebyshev semi-iteration.

TPU-native analog of ref: algorithms/Krylov/{LSQR,CG,FlexibleCG,Chebyshev}.hpp.
All solvers are jittable: the iteration is a ``lax.while_loop`` whose carry
holds the Krylov vectors plus per-column scalar recurrences as (k,) arrays —
the TPU form of the reference's "replicated scalars" pattern
(ref: algorithms/Krylov/internal.hpp:13-39, where scalar containers are
[STAR,STAR] so every rank steps the recurrence identically). Under a sharded
operator the matvecs carry the collectives; the scalar math is replicated.

Operators are either jnp matrices or (matvec, rmatvec) callables, so the same
code serves dense sharded arrays, sparse containers, and implicit operators
(e.g. Gram matrices, SMW-preconditioned systems).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

import jax.numpy as jnp
from jax import lax

from libskylark_tpu.algorithms.precond import IdPrecond, Precond
from libskylark_tpu.base.params import Params
from libskylark_tpu.base.precision import with_solver_precision

Operator = Union[jnp.ndarray, Tuple[Callable, Callable]]


@dataclasses.dataclass
class KrylovParams(Params):
    """ref: algorithms/Krylov/krylov_iter_params.hpp:8."""

    tolerance: float = 1e-6
    iter_lim: int = -1


def _as_ops(A: Operator):
    """(mv, rmv) over any operand kind: explicit pair, dense array,
    SparseMatrix, or DistSparseMatrix (the reference's matrix-type
    templating of the Krylov loops, ref: algorithms/Krylov/LSQR.hpp:21)."""
    if isinstance(A, tuple):
        return A
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix
    from libskylark_tpu.base.sparse import SparseMatrix, spmm, spmm_t

    if isinstance(A, SparseMatrix):
        return (lambda x: spmm(A, x)), (lambda x: spmm_t(A, x))
    if isinstance(A, DistSparseMatrix):
        return A.spmm, A.spmm_t
    M = jnp.asarray(A)
    return (lambda x: M @ x), (lambda x: M.T @ x)


def _colnorms(X):
    return jnp.sqrt(jnp.sum(X * X, axis=0))


def lsqr_parts(
    A: Operator,
    B: jnp.ndarray,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    shape: Optional[Tuple[int, int]] = None,
):
    """The LSQR iteration taken apart: ``(state0, body, meta)``.

    ``state0`` is the initial carry (a dict of jnp arrays — everything
    the recurrence needs, nothing more), ``body`` the pure
    one-iteration transition ``state -> state``, and ``meta`` the
    loop-free facts (``iter_lim``, ``squeeze``, ``extract`` pulling the
    solution out of a carry). :func:`lsqr` runs body under the default
    convergence cond; the train slice engines
    (:mod:`libskylark_tpu.train.slices`) run the *same* body under a
    bounded cond so a job advances k iterations per slice and the
    carried state round-trips through checkpoints bit-equal. Both
    paths share these parts by construction — a numerics change here
    changes the one-shot solver and the sliced solver together."""
    params = params or KrylovParams()
    mv, rmv = _as_ops(A)
    R = precond or IdPrecond()
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if shape is None:
        if isinstance(A, tuple):
            raise ValueError("shape=(m, n) required for operator-pair A")
        shape = A.shape if hasattr(A, "shape") else jnp.asarray(A).shape
    m, n = shape
    k = B.shape[1]
    dt = B.dtype

    eps = 32 * jnp.finfo(dt).eps
    tol = min(max(params.tolerance, float(eps)), 1.0 - float(eps))
    iter_lim = params.iter_lim if params.iter_lim > 0 else max(20, 2 * min(m, n))

    beta = _colnorms(B)
    U = B / jnp.maximum(beta, eps)[None, :]
    V = R.apply_adjoint(rmv(U))
    alpha = _colnorms(V)
    V = V / jnp.maximum(alpha, eps)[None, :]
    Z = R.apply(V)
    W = Z
    X = jnp.zeros((n, k), dt)
    nrm_ar_0 = alpha * beta

    state = dict(
        X=X, U=U, V=V, Z=Z, W=W,
        alpha=alpha, beta=beta,
        phibar=beta, rhobar=alpha,
        nrm_a=jnp.zeros((k,), dt),
        nrm_r=beta,
        done=(nrm_ar_0 == 0),
        it=jnp.int32(0),
    )

    def body(s):
        # Bidiagonalization step (ref: LSQR.hpp:114-135)
        U = mv(s["Z"]) - s["alpha"][None, :] * s["U"]
        beta = _colnorms(U)
        U = U / jnp.maximum(beta, eps)[None, :]
        V = R.apply_adjoint(rmv(U)) - beta[None, :] * s["V"]
        alpha = _colnorms(V)
        V = V / jnp.maximum(alpha, eps)[None, :]
        Z = R.apply(V)

        nrm_a = jnp.sqrt(s["nrm_a"] ** 2 + s["alpha"] ** 2 + beta**2)

        # Givens rotation (ref: LSQR.hpp:150-170)
        rho = jnp.sqrt(s["rhobar"] ** 2 + beta**2)
        cs = s["rhobar"] / rho
        sn = beta / rho
        theta = sn * alpha
        rhobar = -cs * alpha
        phi = cs * s["phibar"]
        phibar = sn * s["phibar"]

        step = (phi / rho)[None, :] * s["W"]
        X = jnp.where(s["done"][None, :], s["X"], s["X"] + step)
        W = Z - (theta / rho)[None, :] * s["W"]

        nrm_r = phibar
        nrm_ar = phibar * alpha * jnp.abs(cs)
        done = s["done"] | (nrm_ar <= tol * jnp.maximum(nrm_a * nrm_r, eps)) | (
            nrm_ar <= tol * nrm_ar_0
        )
        return dict(
            X=X, U=U, V=V, Z=Z, W=W, alpha=alpha, beta=beta,
            phibar=phibar, rhobar=rhobar, nrm_a=nrm_a, nrm_r=nrm_r,
            done=done, it=s["it"] + 1,
        )

    meta = dict(iter_lim=iter_lim, squeeze=squeeze,
                extract=lambda s: s["X"][:, 0] if squeeze else s["X"])
    return state, body, meta


@with_solver_precision
def lsqr(
    A: Operator,
    B: jnp.ndarray,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    shape: Optional[Tuple[int, int]] = None,
):
    """Paige-Saunders LSQR for min ‖A·X − B‖ with optional right
    preconditioner R (ref: algorithms/Krylov/LSQR.hpp:21-299): the iteration
    runs on A·R and the solution accumulates in the original space via
    Z = R·V, exactly as the reference threads ``R.apply``/``apply_adjoint``.

    Returns (X, iterations). B may have k columns; each column has its own
    scalar recurrence and stopping state.
    """
    state, body, meta = lsqr_parts(A, B, params, precond, shape)
    iter_lim = meta["iter_lim"]

    def cond(s):
        return (s["it"] < iter_lim) & (~jnp.all(s["done"]))

    out = lax.while_loop(cond, body, state)
    return meta["extract"](out), out["it"]


def cg_parts(
    A: Operator,
    B: jnp.ndarray,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    X0: Optional[jnp.ndarray] = None,
    shape: Optional[Tuple[int, int]] = None,
):
    """The CG iteration taken apart — see :func:`lsqr_parts` for the
    contract. ``shape`` is accepted for signature symmetry (CG systems
    are square; B fixes the size)."""
    del shape
    params = params or KrylovParams()
    mv, _ = _as_ops(A)
    M = precond or IdPrecond()
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    n, k = B.shape
    dt = B.dtype
    eps = jnp.finfo(dt).eps
    iter_lim = params.iter_lim if params.iter_lim > 0 else max(20, 2 * n)
    tol = params.tolerance

    X = jnp.zeros_like(B) if X0 is None else jnp.asarray(X0).reshape(n, k)
    Rr = B - mv(X)
    Zz = M.apply(Rr)
    P = Zz
    rz = jnp.sum(Rr * Zz, axis=0)
    nrm_b = jnp.maximum(_colnorms(B), eps)

    state = dict(X=X, R=Rr, P=P, rz=rz, it=jnp.int32(0),
                 done=(_colnorms(Rr) <= tol * nrm_b))

    def body(s):
        AP = mv(s["P"])
        pap = jnp.sum(s["P"] * AP, axis=0)
        alpha = s["rz"] / jnp.where(pap == 0, 1.0, pap)
        alpha = jnp.where(s["done"], 0.0, alpha)
        X = s["X"] + alpha[None, :] * s["P"]
        Rr = s["R"] - alpha[None, :] * AP
        Zz = M.apply(Rr)
        rz_new = jnp.sum(Rr * Zz, axis=0)
        beta = rz_new / jnp.where(s["rz"] == 0, 1.0, s["rz"])
        P = Zz + beta[None, :] * s["P"]
        done = s["done"] | (_colnorms(Rr) <= tol * nrm_b)
        return dict(X=X, R=Rr, P=P, rz=rz_new, it=s["it"] + 1, done=done)

    meta = dict(iter_lim=iter_lim, squeeze=squeeze,
                extract=lambda s: s["X"][:, 0] if squeeze else s["X"])
    return state, body, meta


@with_solver_precision
def cg(
    A: Operator,
    B: jnp.ndarray,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    X0: Optional[jnp.ndarray] = None,
    shape: Optional[Tuple[int, int]] = None,
):
    """Preconditioned conjugate gradient for SPD A
    (ref: algorithms/Krylov/CG.hpp:23). Returns (X, iterations)."""
    state, body, meta = cg_parts(A, B, params, precond, X0, shape)
    iter_lim = meta["iter_lim"]

    def cond(s):
        return (s["it"] < iter_lim) & (~jnp.all(s["done"]))

    out = lax.while_loop(cond, body, state)
    return meta["extract"](out), out["it"]


@with_solver_precision
def flexible_cg(
    A: Operator,
    B: jnp.ndarray,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    X0: Optional[jnp.ndarray] = None,
):
    """Flexible CG (Polak-Ribiere beta) tolerating a varying preconditioner
    (ref: algorithms/Krylov/FlexibleCG.hpp:23). The preconditioner may be a
    ``Precond`` or a callable ``(R, it) -> Z`` (inner iterative solves)."""
    params = params or KrylovParams()
    mv, _ = _as_ops(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    n, k = B.shape
    dt = B.dtype
    eps = jnp.finfo(dt).eps
    iter_lim = params.iter_lim if params.iter_lim > 0 else max(20, 2 * n)
    tol = params.tolerance

    if precond is None:
        apply_m = lambda Rr, it: Rr
    elif isinstance(precond, Precond):
        apply_m = lambda Rr, it: precond.apply(Rr)
    else:
        apply_m = precond

    X = jnp.zeros_like(B) if X0 is None else jnp.asarray(X0).reshape(n, k)
    Rr = B - mv(X)
    nrm_b = jnp.maximum(_colnorms(B), eps)
    Z = apply_m(Rr, jnp.int32(0))
    P = Z

    state = dict(X=X, R=Rr, P=P, Zprev=Z, it=jnp.int32(0),
                 done=(_colnorms(Rr) <= tol * nrm_b))

    def cond(s):
        return (s["it"] < iter_lim) & (~jnp.all(s["done"]))

    def body(s):
        AP = mv(s["P"])
        pap = jnp.sum(s["P"] * AP, axis=0)
        rz = jnp.sum(s["R"] * s["Zprev"], axis=0)
        alpha = rz / jnp.where(pap == 0, 1.0, pap)
        alpha = jnp.where(s["done"], 0.0, alpha)
        X = s["X"] + alpha[None, :] * s["P"]
        Rn = s["R"] - alpha[None, :] * AP
        Zn = apply_m(Rn, s["it"] + 1)
        # Polak-Ribiere: beta = z_new·(r_new − r_old) / z_old·r_old
        num = jnp.sum(Zn * (Rn - s["R"]), axis=0)
        beta = num / jnp.where(rz == 0, 1.0, rz)
        P = Zn + beta[None, :] * s["P"]
        done = s["done"] | (_colnorms(Rn) <= tol * nrm_b)
        return dict(X=X, R=Rn, P=P, Zprev=Zn, it=s["it"] + 1, done=done)

    out = lax.while_loop(cond, body, state)
    X = out["X"][:, 0] if squeeze else out["X"]
    return X, out["it"]


@with_solver_precision
def chebyshev(
    A: Operator,
    B: jnp.ndarray,
    lambda_min: float,
    lambda_max: float,
    params: Optional[KrylovParams] = None,
    precond: Optional[Precond] = None,
    X0: Optional[jnp.ndarray] = None,
):
    """Chebyshev semi-iteration for SPD A with spectrum in
    [lambda_min, lambda_max] (ref: algorithms/Krylov/Chebyshev.hpp:18).
    Matvec-only inner loop — no inner products, hence no collectives beyond
    the operator itself: the communication-optimal choice on a mesh."""
    params = params or KrylovParams()
    mv, _ = _as_ops(A)
    M = precond or IdPrecond()
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    dt = B.dtype
    iter_lim = params.iter_lim if params.iter_lim > 0 else 50

    d = (lambda_max + lambda_min) / 2.0
    c = (lambda_max - lambda_min) / 2.0
    X = jnp.zeros_like(B) if X0 is None else jnp.asarray(X0).reshape(B.shape)

    def body(i, carry):
        X, P, alpha_prev = carry
        Rr = B - mv(X)
        Z = M.apply(Rr)
        beta = jnp.where(i == 0, 0.0,
                         jnp.where(i == 1, 0.5 * (c * alpha_prev) ** 2,
                                   (c * alpha_prev / 2.0) ** 2))
        alpha = jnp.where(i == 0, 1.0 / d, 1.0 / (d - beta / alpha_prev))
        P = Z + beta * P
        X = X + alpha * P
        return (X, P, alpha)

    X, _, _ = lax.fori_loop(0, iter_lim, body,
                            (X, jnp.zeros_like(B), jnp.asarray(1.0, dt)))
    return (X[:, 0] if squeeze else X), jnp.int32(iter_lim)
