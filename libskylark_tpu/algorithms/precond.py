"""Preconditioner protocol for Krylov solvers.

TPU-native analog of ref: algorithms/Krylov/precond.hpp:14-120 — identity,
matrix-multiply, and triangular-inverse preconditioners. The reference's
inplace/outplace split disappears (jax arrays are immutable); a preconditioner
is an object with ``apply`` and ``apply_adjoint`` acting on (n, k) blocks.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl


class Precond:
    def apply(self, X: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def apply_adjoint(self, X: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


class IdPrecond(Precond):
    """Identity (ref: precond.hpp:14-31)."""

    def apply(self, X):
        return X

    def apply_adjoint(self, X):
        return X


class MatPrecond(Precond):
    """Multiply by a fixed matrix M (ref: precond.hpp mat_precond_t)."""

    def __init__(self, M: jnp.ndarray):
        self.M = jnp.asarray(M)

    def apply(self, X):
        return self.M @ X

    def apply_adjoint(self, X):
        return self.M.T @ X


class TriInversePrecond(Precond):
    """Apply R⁻¹ for a triangular R via trsm (ref: precond.hpp
    tri_inverse_precond_t) — the Blendenpik right-preconditioner."""

    def __init__(self, R: jnp.ndarray, lower: bool = False):
        self.R = jnp.asarray(R)
        self.lower = lower

    def apply(self, X):
        return jsl.solve_triangular(self.R, X, lower=self.lower)

    def apply_adjoint(self, X):
        return jsl.solve_triangular(self.R, X, lower=self.lower, trans="T")


class FunctionPrecond(Precond):
    """Arbitrary callable pair — used by e.g. the random-features KRR
    preconditioner (ml/krr.hpp:310-398 analog)."""

    def __init__(self, fn, fn_adjoint=None):
        self._fn = fn
        self._fn_adj = fn_adjoint or fn

    def apply(self, X):
        return self._fn(X)

    def apply_adjoint(self, X):
        return self._fn_adj(X)
