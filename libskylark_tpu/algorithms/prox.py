"""Losses and regularizers with proximal operators.

TPU-native analog of ref: algorithms/regression/loss.hpp:7-430 and
algorithms/regression/regularizers.hpp:7-90. These drive the ADMM kernel
machines (ml/BlockADMM) and the hilbert-space models.

Conventions follow the reference:
- ``O``/``X`` is (k, n): k outputs (1 for regression, #classes for
  classification), n examples.
- ``T`` is the target: for k == 1 it is the (n,) value/±1-label vector; for
  k > 1 it is the (n,) integer class-label vector and targets are one-vs-all
  encoded ±1 on the fly (ref: loss.hpp:52-58).
- ``proxoperator(X, lam, T)`` returns argmin_Y loss(Y, T) + 1/(2·lam)‖Y−X‖².

Everything is elementwise/vectorized jnp — the reference's OpenMP loops
disappear into the VPU. The logistic prox replaces the reference's per-sample
Newton-with-line-search C routine (ref: loss.hpp:362-430 ``logexp``) with a
fixed-iteration damped-Newton solved batched across samples (bounded static
loop for jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _expand_targets(T: jnp.ndarray, k: int) -> jnp.ndarray:
    """(n,) labels -> (k, n) ±1 one-vs-all matrix when k > 1; passthrough
    reshaped otherwise (ref: loss.hpp:52-58)."""
    T = jnp.asarray(T)
    if k == 1:
        return T.reshape(1, -1)
    labels = T.reshape(-1).astype(jnp.int32)
    return jnp.where(
        jnp.arange(k)[:, None] == labels[None, :], 1.0, -1.0
    )


class Loss:
    """Interface (ref: loss.hpp:7-21)."""

    name = "loss"

    def evaluate(self, O: jnp.ndarray, T: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def prox(self, X: jnp.ndarray, lam: float, T: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


class SquaredLoss(Loss):
    """0.5‖O − T‖²_F (ref: loss.hpp:26-105)."""

    name = "squared"

    def evaluate(self, O, T):
        Tm = _expand_targets(T, O.shape[0])
        return 0.5 * jnp.sum((O - Tm) ** 2)

    def prox(self, X, lam, T):
        Tm = _expand_targets(T, X.shape[0])
        return (X + lam * Tm) / (1.0 + lam)


class LADLoss(Loss):
    """Least absolute deviations ‖O − T‖₁; prox = soft clamp toward target
    (ref: loss.hpp:107-197)."""

    name = "lad"

    def evaluate(self, O, T):
        Tm = _expand_targets(T, O.shape[0])
        return jnp.sum(jnp.abs(O - Tm))

    def prox(self, X, lam, T):
        Tm = _expand_targets(T, X.shape[0])
        return jnp.where(
            X > Tm + lam, X - lam, jnp.where(X < Tm - lam, X + lam, Tm)
        )


class HingeLoss(Loss):
    """Σ max(1 − t·o, 0) (ref: loss.hpp:203-307)."""

    name = "hinge"

    def evaluate(self, O, T):
        Tm = _expand_targets(T, O.shape[0])
        return jnp.sum(jnp.maximum(1.0 - Tm * O, 0.0))

    def prox(self, X, lam, T):
        Tm = _expand_targets(T, X.shape[0])
        yv = Tm * X
        return jnp.where(
            yv > 1.0, X, jnp.where(yv < 1.0 - lam, X + lam * Tm, Tm)
        )


class LogisticLoss(Loss):
    """Multiclass logistic: Σᵢ −o_{tᵢ,i} + logsumexp(o_{:,i})
    (ref: loss.hpp:309-360). Prox solved by batched damped Newton
    (replacing the per-sample C solver, ref: loss.hpp:365-430)."""

    name = "logistic"

    def __init__(self, newton_iters: int = 30):
        self._iters = int(newton_iters)

    def evaluate(self, O, T):
        labels = jnp.asarray(T).reshape(-1).astype(jnp.int32)
        picked = O[labels, jnp.arange(O.shape[1])]
        return jnp.sum(-picked + jax.scipy.special.logsumexp(O, axis=0))

    def prox(self, X, lam, T):
        # argmin_x  -x_t + logsumexp(x) + 1/(2 lam) ||x - v||^2, per column.
        # Matches the reference's parameterization: its `logexp` is called
        # with lambda_ref = 1/lam (ref: loss.hpp:344).
        k, n = X.shape
        labels = jnp.asarray(T).reshape(-1).astype(jnp.int32)
        E = (jnp.arange(k)[:, None] == labels[None, :]).astype(X.dtype)
        ilam = 1.0 / lam

        def body(x, _):
            p = jax.nn.softmax(x, axis=0)
            grad = p - E + ilam * (x - X)
            # Diagonal-dominant Hessian approx: diag(p) + ilam (drops the
            # rank-1 -pp^T term, then compensates with the same projection
            # the reference uses).
            u = grad / (p + ilam)
            z = p / (p + ilam)
            pu = jnp.sum(p * u, axis=0, keepdims=True)
            pptil = 1.0 - jnp.sum(z * p, axis=0, keepdims=True)
            u = u - (pu / jnp.maximum(pptil, 1e-12)) * z
            return x - 0.5 * u, None

        x, _ = lax.scan(body, X, None, length=self._iters)
        return x


class Regularizer:
    """Interface (ref: regularizers.hpp:7-20). ``prox(W, lam, mu)`` returns
    argmin_P r(P) + 1/(2·lam)‖P − (W − mu)‖² per the reference's convention
    of shifting by the dual variable mu."""

    name = "regularizer"

    def evaluate(self, W: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def prox(self, W: jnp.ndarray, lam: float, mu: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


class EmptyRegularizer(Regularizer):
    """No regularization (ref: regularizers.hpp:22-36)."""

    name = "none"

    def evaluate(self, W):
        return jnp.asarray(0.0, W.dtype)

    def prox(self, W, lam, mu):
        return W - mu


class L2Regularizer(Regularizer):
    """0.5‖W‖²; shrink (ref: regularizers.hpp:38-62)."""

    name = "l2"

    def evaluate(self, W):
        return 0.5 * jnp.sum(W * W)

    def prox(self, W, lam, mu):
        return (W - mu) / (1.0 + lam)


class L1Regularizer(Regularizer):
    """‖W‖₁; soft-threshold (ref: regularizers.hpp:64-90)."""

    name = "l1"

    def evaluate(self, W):
        return jnp.sum(jnp.abs(W))

    def prox(self, W, lam, mu):
        V = W - mu
        return jnp.sign(V) * jnp.maximum(jnp.abs(V) - lam, 0.0)


LOSSES = {c.name: c for c in [SquaredLoss, LADLoss, HingeLoss, LogisticLoss]}
REGULARIZERS = {c.name: c for c in [EmptyRegularizer, L2Regularizer, L1Regularizer]}
