"""Regression framework: exact, sketched, and sketch-accelerated solvers.

TPU-native analog of the reference's tag-dispatched regression framework
(ref: algorithms/regression/regression_problem.hpp:10-84,
linearl2_regression_solver_Elemental.hpp:23-163,
sketched_regression_solver.hpp:12-28,
accelerated_linearl2_regression_solver_Elemental.hpp:10-276).

The compile-time tag algebra (problem type × penalty × regularization ×
algorithm tag) becomes plain runtime parameters — Python already dispatches
dynamically, and XLA specializes per shape at trace time, which is where the
reference's template instantiation actually paid off.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from libskylark_tpu.algorithms import krylov
from libskylark_tpu.algorithms.precond import MatPrecond, Precond, TriInversePrecond
from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.base.precision import with_solver_precision


@dataclasses.dataclass
class RegressionProblem:
    """min ‖A·x − b‖ with the reference's problem algebra
    (ref: regression_problem.hpp:10-58)."""

    A: jnp.ndarray
    kind: str = "linear"  # linear | polynomial | kernel
    penalty: str = "l2"  # l2 | l1 | lp
    regularization: Optional[str] = None


# -- exact L2 solvers (ref: linearl2_regression_solver_Elemental.hpp) --


@with_solver_precision
def solve_l2_exact(A: jnp.ndarray, B: jnp.ndarray, method: str = "qr") -> jnp.ndarray:
    """Exact least squares min ‖A·X − B‖ by the requested algorithm tag
    (ref: linearl2_regression_solver.hpp:11-37 — qr/sne/ne/svd)."""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if method == "qr":
        Q, R = jnp.linalg.qr(A)
        X = jsl.solve_triangular(R, Q.T @ B, lower=False)
    elif method == "sne":
        # Semi-normal equations: R from QR(A), solve RᵀR X = AᵀB.
        _, R = jnp.linalg.qr(A)
        Y = jsl.solve_triangular(R, A.T @ B, lower=False, trans="T")
        X = jsl.solve_triangular(R, Y, lower=False)
    elif method == "ne":
        G = A.T @ A
        L = jnp.linalg.cholesky(G)
        Y = jsl.solve_triangular(L, A.T @ B, lower=True)
        X = jsl.solve_triangular(L, Y, lower=True, trans="T")
    elif method == "svd":
        U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
        s_inv = jnp.where(s > s[0] * jnp.finfo(A.dtype).eps * max(A.shape), 1.0 / s, 0.0)
        X = Vt.T @ (s_inv[:, None] * (U.T @ B))
    else:
        raise errors.InvalidParametersError(f"unknown exact l2 method {method!r}")
    return X[:, 0] if squeeze else X


# -- sketch-and-solve (ref: sketched_regression_solver.hpp:12-28) --


@with_solver_precision
def solve_l2_sketched(
    A: jnp.ndarray,
    B: jnp.ndarray,
    transform,
    method: str = "qr",
) -> jnp.ndarray:
    """Sketch-and-solve: compress rows of [A | B] with any columnwise sketch
    transform, then solve the small problem exactly
    (ref: sketched_regression_solver_Elemental.hpp — sketch to [STAR,STAR]
    and solve locally; here the small problem is replicated by construction)."""
    from libskylark_tpu.sketch import COLUMNWISE

    B = jnp.asarray(B)
    squeeze = B.ndim == 1  # sketch apply promotes vectors to (N, 1)
    SA = transform.apply(A, COLUMNWISE)
    SB = transform.apply(B, COLUMNWISE)
    X = solve_l2_exact(SA, SB, method=method)
    return X[:, 0] if squeeze else X


# -- accelerated solvers (ref: accelerated_linearl2_regression_solver_*) --


@dataclasses.dataclass
class AcceleratedParams(Params):
    """Knobs of the Blendenpik/LSRN family."""

    sketch_size_factor: float = 4.0  # s = factor × n
    tolerance: float = 1e-10
    iter_lim: int = -1
    cond_threshold: float = 1e7  # fallback to exact SVD if precond this bad
    sketch: str = "fjlt"  # fjlt | jlt | cwt


@with_solver_precision
def build_blendenpik_precond(
    A: jnp.ndarray, context: Context, params: AcceleratedParams
) -> tuple[Precond, jnp.ndarray]:
    """Sketch A and QR the sketch; R is the right preconditioner
    (ref: accelerated_linearl2_regression_solver_Elemental.hpp:68-77)."""
    from libskylark_tpu import sketch as sk

    m, n = A.shape
    s = int(params.sketch_size_factor * n)
    s = min(max(s, n + 1), m)
    if params.sketch == "fjlt":
        T = sk.FJLT(m, s, context)
    elif params.sketch == "jlt":
        T = sk.JLT(m, s, context)
    elif params.sketch == "cwt":
        T = sk.CWT(m, max(s, 4 * n), context)
    else:
        raise errors.InvalidParametersError(f"unknown sketch {params.sketch!r}")
    SA = T.apply(A, sk.COLUMNWISE)
    R = jnp.linalg.qr(SA, mode="r")
    return TriInversePrecond(R), R


@with_solver_precision
def build_lsrn_precond(
    A: jnp.ndarray, context: Context, params: AcceleratedParams
) -> tuple[Precond, jnp.ndarray]:
    """LSRN: Gaussian sketch, SVD of the sketch, precond N = V·Σ⁻¹
    (ref: accelerated_linearl2_regression_solver.hpp lsrn_tag)."""
    from libskylark_tpu import sketch as sk

    m, n = A.shape
    s = int(params.sketch_size_factor * n)
    s = min(max(s, n + 1), m)
    T = sk.JLT(m, s, context)
    SA = T.apply(A, sk.COLUMNWISE)
    _, sv, Vt = jnp.linalg.svd(SA, full_matrices=False)
    Ninv = Vt.T * (1.0 / jnp.maximum(sv, sv[0] * jnp.finfo(A.dtype).eps))[None, :]
    return MatPrecond(Ninv), sv


@with_solver_precision
def solve_l2_accelerated(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    method: str = "blendenpik",
    params: Optional[AcceleratedParams] = None,
):
    """Sketch-preconditioned LSQR (Blendenpik / LSRN / simplified variant)
    with an ill-conditioning fallback to the exact SVD solver
    (ref: accelerated_linearl2_regression_solver_Elemental.hpp:208-276).

    Returns (X, iterations); iterations == 0 signals the exact fallback.

    ``A`` may be dense, a :class:`SparseMatrix`, or a
    :class:`DistSparseMatrix` — sparse operands default the sketch to CWT
    (the reference's sparse-input path; the FJLT needs a dense fast
    transform) and run LSQR through the sparse matvecs.
    """
    from libskylark_tpu.base.sparse import is_sparse_operand

    params = params or AcceleratedParams()
    is_sparse = is_sparse_operand(A)
    if is_sparse:
        if params.sketch == "fjlt":
            params = dataclasses.replace(params, sketch="cwt")
    else:
        A = jnp.asarray(A)
    B = jnp.asarray(B)

    if method in ("blendenpik", "simplified_blendenpik"):
        if method == "simplified_blendenpik":
            p2 = dataclasses.replace(params, sketch="cwt")
            precond, R = build_blendenpik_precond(A, context, p2)
        else:
            precond, R = build_blendenpik_precond(A, context, params)
        # Condition of the small R factor — the reference runs CondEst
        # and falls back to the exact SVD solver (ref: :241-253).
        cond = jnp.linalg.cond(R)
    elif method == "lsrn":
        precond, sv = build_lsrn_precond(A, context, params)
        cond = sv[0] / jnp.maximum(sv[-1], jnp.finfo(A.dtype).tiny)
    else:
        raise errors.InvalidParametersError(f"unknown accelerated method {method!r}")

    if not bool(jnp.isfinite(cond)) or float(cond) > params.cond_threshold:
        # exact fallback is a dense factorization (as in the reference)
        Ad = A.todense() if is_sparse else A
        return solve_l2_exact(Ad, B, method="svd"), jnp.int32(0)

    kp = krylov.KrylovParams(tolerance=params.tolerance, iter_lim=params.iter_lim)
    return krylov.lsqr(A, B, params=kp, precond=precond)
