"""Regression framework: exact, sketched, and sketch-accelerated solvers.

TPU-native analog of the reference's tag-dispatched regression framework
(ref: algorithms/regression/regression_problem.hpp:10-84,
linearl2_regression_solver_Elemental.hpp:23-163,
sketched_regression_solver.hpp:12-28,
accelerated_linearl2_regression_solver_Elemental.hpp:10-276).

The compile-time tag algebra (problem type × penalty × regularization ×
algorithm tag) becomes plain runtime parameters — Python already dispatches
dynamically, and XLA specializes per shape at trace time, which is where the
reference's template instantiation actually paid off.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from libskylark_tpu import engine
from libskylark_tpu.algorithms import krylov
from libskylark_tpu.algorithms.precond import MatPrecond, Precond, TriInversePrecond
from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.base.precision import with_solver_precision


@dataclasses.dataclass
class RegressionProblem:
    """min ‖A·x − b‖ with the reference's problem algebra
    (ref: regression_problem.hpp:10-58)."""

    A: jnp.ndarray
    kind: str = "linear"  # linear | polynomial | kernel
    penalty: str = "l2"  # l2 | l1 | lp
    regularization: Optional[str] = None


# -- exact L2 solvers (ref: linearl2_regression_solver_Elemental.hpp) --


@with_solver_precision
def solve_l2_exact(A: jnp.ndarray, B: jnp.ndarray, method: str = "qr") -> jnp.ndarray:
    """Exact least squares min ‖A·X − B‖ by the requested algorithm tag
    (ref: linearl2_regression_solver.hpp:11-37 — qr/sne/ne/svd)."""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if method == "qr":
        Q, R = jnp.linalg.qr(A)
        X = jsl.solve_triangular(R, Q.T @ B, lower=False)
    elif method == "sne":
        # Semi-normal equations: R from QR(A), solve RᵀR X = AᵀB.
        _, R = jnp.linalg.qr(A)
        Y = jsl.solve_triangular(R, A.T @ B, lower=False, trans="T")
        X = jsl.solve_triangular(R, Y, lower=False)
    elif method == "ne":
        G = A.T @ A
        L = jnp.linalg.cholesky(G)
        Y = jsl.solve_triangular(L, A.T @ B, lower=True)
        X = jsl.solve_triangular(L, Y, lower=True, trans="T")
    elif method == "svd":
        U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
        s_inv = jnp.where(s > s[0] * jnp.finfo(A.dtype).eps * max(A.shape), 1.0 / s, 0.0)
        X = Vt.T @ (s_inv[:, None] * (U.T @ B))
    else:
        raise errors.InvalidParametersError(f"unknown exact l2 method {method!r}")
    return X[:, 0] if squeeze else X


# -- sketch-and-solve (ref: sketched_regression_solver.hpp:12-28) --


@with_solver_precision
def solve_l2_sketched(
    A: jnp.ndarray,
    B: jnp.ndarray,
    transform,
    method: str = "qr",
) -> jnp.ndarray:
    """Sketch-and-solve: compress rows of [A | B] with any columnwise sketch
    transform, then solve the small problem exactly
    (ref: sketched_regression_solver_Elemental.hpp — sketch to [STAR,STAR]
    and solve locally; here the small problem is replicated by construction).

    Dense operands run sketch + solve as one engine-compiled executable
    (keyed on the transform's serialization digest); sparse operands and
    calls inside a user jit take the direct path."""
    from libskylark_tpu.base.sparse import is_sparse_operand
    from libskylark_tpu.sketch import COLUMNWISE

    B = jnp.asarray(B)
    squeeze = B.ndim == 1  # sketch apply promotes vectors to (N, 1)

    def solve(A, B):
        SA = transform.apply(A, COLUMNWISE)
        SB = transform.apply(B, COLUMNWISE)
        return solve_l2_exact(SA, SB, method=method)

    if is_sparse_operand(A) or isinstance(A, jax.core.Tracer) \
            or isinstance(B, jax.core.Tracer):
        X = solve(A, B)
    else:
        cf = engine.compiled(
            solve, name="solve_l2_sketched", donate_argnums=(0, 1),
            donate="auto",
            key_fn=lambda *a: (engine.digest(transform), method))
        X = cf(jnp.asarray(A), B)
    return X[:, 0] if squeeze else X


def sketched_solve_serve(key_data, scale, A, B, *, sketch_type: str,
                         s_dim: int, method: str = "qr") -> jnp.ndarray:
    """Pure, vmap-batchable sketch-and-solve for the microbatch serving
    layer (:mod:`libskylark_tpu.engine.serve`): rebuilds the row sketch
    from the transform's raw key data and solves the compressed problem
    — the whole request is one traceable function of
    ``(key_data, scale, A, B)`` with the sketch family and method
    static. Zero-padding the row dimension of A/B is exact (padded rows
    contribute nothing through either sketch family); the feature and
    target dimensions are NOT paddable (a zero feature column makes the
    small problem singular), so the serving bucket keys them exactly."""
    from libskylark_tpu.base import randgen
    from libskylark_tpu.sketch import dense, hash as sketch_hash

    if sketch_type == "CWT":
        SA = sketch_hash.cwt_serve_apply(key_data, A, s_dim=s_dim,
                                         rowwise=False)
        SB = sketch_hash.cwt_serve_apply(key_data, B, s_dim=s_dim,
                                         rowwise=False)
    elif sketch_type == "JLT":
        SA = dense.serve_apply(key_data, scale, A,
                               dist=randgen.Normal(), s_dim=s_dim,
                               rowwise=False)
        SB = dense.serve_apply(key_data, scale, B,
                               dist=randgen.Normal(), s_dim=s_dim,
                               rowwise=False)
    else:
        raise errors.InvalidParametersError(
            f"serve path supports JLT/CWT sketches, got {sketch_type!r}")
    return solve_l2_exact(SA, SB, method=method)


# -- accelerated solvers (ref: accelerated_linearl2_regression_solver_*) --


@dataclasses.dataclass
class AcceleratedParams(Params):
    """Knobs of the Blendenpik/LSRN family."""

    sketch_size_factor: float = 4.0  # s = factor × n
    tolerance: float = 1e-10
    iter_lim: int = -1
    cond_threshold: float = 1e7  # fallback to exact SVD if precond this bad
    sketch: str = "fjlt"  # fjlt | jlt | cwt


def _accel_transform(m: int, n: int, context: Context,
                     params: AcceleratedParams, *, gaussian: bool = False):
    """The row-compressing sketch of the accelerated family; allocated
    eagerly (advances the Context counter) so the compiled solve phases
    can be keyed on its serialization digest."""
    from libskylark_tpu import sketch as sk

    s = int(params.sketch_size_factor * n)
    s = min(max(s, n + 1), m)
    if gaussian:
        return sk.JLT(m, s, context)
    if params.sketch == "fjlt":
        return sk.FJLT(m, s, context)
    if params.sketch == "jlt":
        return sk.JLT(m, s, context)
    if params.sketch == "cwt":
        return sk.CWT(m, max(s, 4 * n), context)
    raise errors.InvalidParametersError(f"unknown sketch {params.sketch!r}")


def _blendenpik_r(A, T) -> jnp.ndarray:
    """R factor of the sketched operand — the right preconditioner
    (ref: accelerated_linearl2_regression_solver_Elemental.hpp:68-77)."""
    from libskylark_tpu import sketch as sk

    SA = T.apply(A, sk.COLUMNWISE)
    return jnp.linalg.qr(SA, mode="r")


def _lsrn_parts(A, T) -> tuple[jnp.ndarray, jnp.ndarray]:
    """LSRN preconditioner N = V·Σ⁻¹ from the SVD of the sketch
    (ref: accelerated_linearl2_regression_solver.hpp lsrn_tag)."""
    from libskylark_tpu import sketch as sk

    SA = T.apply(A, sk.COLUMNWISE)
    _, sv, Vt = jnp.linalg.svd(SA, full_matrices=False)
    Ninv = Vt.T * (1.0 / jnp.maximum(sv, sv[0] * jnp.finfo(SA.dtype).eps))[None, :]
    return Ninv, sv


@with_solver_precision
def build_blendenpik_precond(
    A: jnp.ndarray, context: Context, params: AcceleratedParams
) -> tuple[Precond, jnp.ndarray]:
    """Sketch A and QR the sketch; R is the right preconditioner
    (ref: accelerated_linearl2_regression_solver_Elemental.hpp:68-77)."""
    T = _accel_transform(*A.shape, context, params)
    R = _blendenpik_r(A, T)
    return TriInversePrecond(R), R


@with_solver_precision
def build_lsrn_precond(
    A: jnp.ndarray, context: Context, params: AcceleratedParams
) -> tuple[Precond, jnp.ndarray]:
    """LSRN: Gaussian sketch, SVD of the sketch, precond N = V·Σ⁻¹
    (ref: accelerated_linearl2_regression_solver.hpp lsrn_tag)."""
    T = _accel_transform(*A.shape, context, params, gaussian=True)
    Ninv, sv = _lsrn_parts(A, T)
    return MatPrecond(Ninv), sv


@with_solver_precision
def solve_l2_accelerated(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    method: str = "blendenpik",
    params: Optional[AcceleratedParams] = None,
):
    """Sketch-preconditioned LSQR (Blendenpik / LSRN / simplified variant)
    with an ill-conditioning fallback to the exact SVD solver
    (ref: accelerated_linearl2_regression_solver_Elemental.hpp:208-276).

    Returns (X, iterations); iterations == 0 signals the exact fallback.

    ``A`` may be dense, a :class:`SparseMatrix`, or a
    :class:`DistSparseMatrix` — sparse operands default the sketch to CWT
    (the reference's sparse-input path; the FJLT needs a dense fast
    transform) and run LSQR through the sparse matvecs.

    Dense operands run as TWO engine-compiled executables — the
    precond-build phase (sketch → factor → condition estimate) and the
    LSQR ``lax.while_loop`` phase — with exactly one scalar host sync
    between them: the reference's CondEst fallback decision
    (ref: :241-253), which is a genuine host branch (the fallback
    traces a completely different program)."""
    from libskylark_tpu.base.sparse import is_sparse_operand

    params = params or AcceleratedParams()
    is_sparse = is_sparse_operand(A)
    if is_sparse:
        if params.sketch == "fjlt":
            params = dataclasses.replace(params, sketch="cwt")
    else:
        A = jnp.asarray(A)
    B = jnp.asarray(B)
    use_engine = (not is_sparse
                  and not isinstance(A, jax.core.Tracer)
                  and not isinstance(B, jax.core.Tracer))

    if method in ("blendenpik", "simplified_blendenpik"):
        p2 = (dataclasses.replace(params, sketch="cwt")
              if method == "simplified_blendenpik" else params)
        if use_engine:
            T = _accel_transform(*A.shape, context, p2)

            def build(A):
                R = _blendenpik_r(A, T)
                # Condition of the small R factor — the reference runs
                # CondEst and falls back to exact SVD (ref: :241-253).
                return R, jnp.linalg.cond(R)

            P, cond = engine.compiled(
                build, name="ls_accel_precond",
                key_fn=lambda *a: (engine.digest(T), method))(A)
            make_precond = TriInversePrecond
        else:
            precond, R = build_blendenpik_precond(A, context, p2)
            cond = jnp.linalg.cond(R)
    elif method == "lsrn":
        if use_engine:
            T = _accel_transform(*A.shape, context, params, gaussian=True)

            def build(A):
                Ninv, sv = _lsrn_parts(A, T)
                return Ninv, sv[0] / jnp.maximum(sv[-1],
                                                 jnp.finfo(sv.dtype).tiny)

            P, cond = engine.compiled(
                build, name="ls_accel_precond",
                key_fn=lambda *a: (engine.digest(T), method))(A)
            make_precond = MatPrecond
        else:
            precond, sv = build_lsrn_precond(A, context, params)
            cond = sv[0] / jnp.maximum(sv[-1], jnp.finfo(A.dtype).tiny)
    else:
        raise errors.InvalidParametersError(f"unknown accelerated method {method!r}")

    if not bool(jnp.isfinite(cond)) or float(cond) > params.cond_threshold:
        # exact fallback is a dense factorization (as in the reference)
        Ad = A.todense() if is_sparse else A
        return solve_l2_exact(Ad, B, method="svd"), jnp.int32(0)

    kp = krylov.KrylovParams(tolerance=params.tolerance, iter_lim=params.iter_lim)
    if use_engine:
        def run_lsqr(A, B, P):
            return krylov.lsqr(A, B, params=kp, precond=make_precond(P))

        return engine.compiled(
            run_lsqr, name="ls_accel_lsqr", donate_argnums=(1,),
            donate="auto",
            key_fn=lambda *a: (method, kp.tolerance, kp.iter_lim))(A, B, P)
    return krylov.lsqr(A, B, params=kp, precond=precond)
