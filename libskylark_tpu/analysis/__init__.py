"""``skylark-lint`` — repo-specific static analysis.

The serving stack's core contracts (compile-once/serve-many, zero
tracer leaks, deadlock-free drain, hermetic replica environments) were
enforced only at runtime, by gates that catch one instance at a time.
This package encodes them as AST-level invariants checked on every
commit (``script/lint``; the ``script/ci`` lint gate). Four rule
families:

- ``jit-purity`` (:mod:`.rules.jit_purity`) — functions reaching
  ``engine.compiled`` / ``jax.jit`` / the serve flush builders must
  not read the environment, wall clocks, host RNG, or mutable module
  globals;
- ``lock-discipline`` (:mod:`.rules.lock_discipline`) — the static
  lock-acquisition graph over the ``base.locks`` site names must stay
  acyclic, and blocking calls / callback fan-outs must not run under a
  held lock;
- ``env-registry`` (:mod:`.rules.env_registry`) — every ``SKYLARK_*``
  environment read goes through :mod:`libskylark_tpu.base.env`;
- ``metric-names`` (:mod:`.rules.metric_names`) — every telemetry
  instrument name is declared once
  (:mod:`libskylark_tpu.telemetry.names`) and Prometheus-renderable.

Workflow: findings suppress per line
(``# skylark-lint: disable=<rule>`` on the line, or alone on the line
above) or live in the committed shrink-only baseline
(``libskylark_tpu/analysis/baseline.json``). See ``docs/analysis.rst``.
"""

from __future__ import annotations

from libskylark_tpu.analysis.core import (
    BASELINE_PATH, Finding, Project, baseline_load, baseline_save,
    compare_to_baseline, registered_rules, run_rules,
)

__all__ = [
    "BASELINE_PATH", "Finding", "Project", "baseline_load",
    "baseline_save", "compare_to_baseline", "registered_rules",
    "run_rules",
]
