"""Best-effort project call graph for the transitive rules.

Resolution is deliberately conservative — a call the grapher cannot
resolve contributes nothing (no edge), so the transitive rules
(jit-purity, lock-discipline) under-approximate rather than hallucinate.
Resolved forms:

- ``f(...)``            — module-level function / nested function in
  the enclosing scope / symbol imported ``from mod import f``;
- ``self.m(...)``       — method of the lexically enclosing class;
- ``cls.m(...)`` / ``Klass.m(...)`` — method of a same-project class;
- ``alias.f(...)``      — function of an imported project module.

Function identity is ``"<module>:<qualpath>"`` where qualpath mirrors
``ast`` nesting (``Class.method``, ``outer.<locals>.inner``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from libskylark_tpu.analysis.core import Module, Project


class FunctionInfo:
    def __init__(self, module: Module, qualname: str,
                 node: ast.AST, cls: Optional[str]):
        self.module = module
        self.qualname = qualname            # "mod:Class.method"
        self.node = node
        self.cls = cls                      # enclosing class name or None
        self.calls: List[Tuple[ast.Call, int]] = []


class CallGraph:
    """Function index + per-call resolution over one project."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {}
        # (module, class) -> {method name -> qualname}
        self._methods: Dict[Tuple[str, str], Dict[str, str]] = {}
        # module -> {top-level fn name -> qualname}
        self._toplevel: Dict[str, Dict[str, str]] = {}
        # module -> {class name}
        self._classes: Dict[str, Set[str]] = {}
        for mod in project.modules.values():
            self._index_module(mod)

    # -- indexing --

    def _index_module(self, mod: Module) -> None:
        self._toplevel.setdefault(mod.modname, {})
        self._classes.setdefault(mod.modname, set())

        def visit(node, prefix: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qp = (f"{prefix}.{child.name}" if prefix
                          else child.name)
                    qn = f"{mod.modname}:{qp}"
                    self.functions[qn] = FunctionInfo(mod, qn, child, cls)
                    if not prefix:
                        self._toplevel[mod.modname][child.name] = qn
                    elif cls is not None and prefix == cls:
                        self._methods.setdefault(
                            (mod.modname, cls), {})[child.name] = qn
                    visit(child, f"{qp}.<locals>", cls)
                elif isinstance(child, ast.ClassDef):
                    self._classes[mod.modname].add(child.name)
                    visit(child, child.name, child.name)
                else:
                    visit(child, prefix, cls)

        visit(mod.tree, "", None)

    # -- resolution --

    def resolve_call(self, mod: Module, fn: FunctionInfo,
                     call: ast.Call) -> Optional[str]:
        """Callee qualname for a Call node, or None when unresolvable."""
        func = call.func
        if isinstance(func, ast.Name):
            return self._resolve_name(mod, fn, func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and fn.cls:
                    return self._methods.get(
                        (mod.modname, fn.cls), {}).get(func.attr)
                if base.id in self._classes.get(mod.modname, ()):
                    return self._methods.get(
                        (mod.modname, base.id), {}).get(func.attr)
                target = mod.resolve_alias_module(base.id)
                if target and target in self.project.modules:
                    return self._toplevel.get(target, {}).get(func.attr)
        return None

    def _resolve_name(self, mod: Module, fn: FunctionInfo,
                      name: str) -> Optional[str]:
        # nested function of any enclosing scope
        prefix = fn.qualname.split(":", 1)[1]
        parts = prefix.split(".")
        for cut in range(len(parts), 0, -1):
            cand = (f"{mod.modname}:"
                    f"{'.'.join(parts[:cut])}.<locals>.{name}")
            if cand in self.functions:
                return cand
        # module-level function
        qn = self._toplevel.get(mod.modname, {}).get(name)
        if qn:
            return qn
        # from mod import f
        target = mod.import_aliases.get(name)
        if target and ":" in target:
            pkg, sym = target.split(":", 1)
            if pkg in self.project.modules:
                return self._toplevel.get(pkg, {}).get(sym)
        return None

    def direct_calls(self, qn: str) -> List[Tuple[str, ast.Call]]:
        """Resolved (callee qualname, call node) pairs made directly
        inside ``qn`` (excluding its nested function bodies)."""
        fn = self.functions[qn]
        out: List[Tuple[str, ast.Call]] = []
        for call in iter_own_nodes(fn.node, ast.Call):
            callee = self.resolve_call(fn.module, fn, call)
            if callee:
                out.append((callee, call))
        return out

    def propagate(self, direct: Dict[str, Set],
                  max_rounds: int = 40) -> Dict[str, Set]:
        """Fixpoint union of per-function fact sets along call edges:
        a function's transitive set = its direct set ∪ every (direct)
        callee's transitive set."""
        edges: Dict[str, List[str]] = {}
        for qn in self.functions:
            edges[qn] = [c for c, _ in self.direct_calls(qn)]
        trans = {qn: set(direct.get(qn, ())) for qn in self.functions}
        for _ in range(max_rounds):
            changed = False
            for qn, callees in edges.items():
                for c in callees:
                    add = trans.get(c, ()) - trans[qn]
                    if add:
                        trans[qn].update(add)
                        changed = True
            if not changed:
                break
        return trans


def iter_own_nodes(fn_node: ast.AST, node_type):
    """Every node of ``node_type`` in a function body, NOT descending
    into nested function/class definitions (their bodies execute under
    their own call, not this one)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, node_type):
            yield node
        stack.extend(ast.iter_child_nodes(node))
