"""Checker framework: parsed project model, rule registry, per-line
suppressions, and the committed shrink-only baseline.

Dependency-free by design (stdlib ``ast`` only): the lint must run on
the bare CI image, before — and regardless of — whatever else the
environment has. Nothing here imports jax or the package's runtime
modules; rules read *source*, not live objects (the one exception is
that rule modules may parse ``base/env.py`` / ``telemetry/names.py``
as text to extract declarations — still no runtime import).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

#: The committed baseline of grandfathered findings. Shrink-only: the
#: gate fails on any finding not in the file (new debt) AND on any
#: entry no longer matching a finding (stale debt — remove the entry
#: when you fix the finding, so the file tracks reality exactly and
#: can only shrink).
BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*skylark-lint:\s*disable=([A-Za-z0-9_,-]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation. ``symbol`` is the stable anchor (qualified
    function, lock site, env/metric name) the baseline keys on —
    never a line number, so unrelated edits don't churn the file."""

    rule: str
    path: str          # package-relative posix path
    line: int
    symbol: str
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.message)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.symbol}: {self.message}")


class Module:
    """One parsed source file: AST + source lines + suppressions +
    import alias map (name -> dotted module target)."""

    def __init__(self, relpath: str, modname: str, source: str):
        self.relpath = relpath
        self.modname = modname
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.suppressed = self._parse_suppressions()
        self.import_aliases = self._parse_imports()

    def _parse_suppressions(self) -> Dict[int, set]:
        """lineno -> suppressed rule names. A directive on a code line
        covers that line; a directive alone on a comment line covers
        the next line (the 79-column escape hatch)."""
        out: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = i + 1 if text.lstrip().startswith("#") else i
            out.setdefault(target, set()).update(rules)
        return out

    def is_suppressed(self, rule: str, lineno: int) -> bool:
        rules = self.suppressed.get(lineno, ())
        return rule in rules or "all" in rules

    def _parse_imports(self) -> Dict[str, str]:
        """Top-level ``import x.y as z`` / ``from p import q as r``
        name bindings, as ``alias -> dotted target`` (modules) or
        ``alias -> dotted.target:name`` (imported symbols)."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    continue  # no relative imports in this repo
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = (
                        f"{node.module}:{a.name}")
        return aliases

    def resolve_alias_module(self, name: str) -> Optional[str]:
        """The dotted module ``name`` is bound to at module scope
        (``_env`` -> ``libskylark_tpu.base.env``), or None."""
        target = self.import_aliases.get(name)
        if target is None or ":" not in target:
            return target
        # ``from pkg import sub`` binds a module when pkg.sub exists as
        # a module path; the project decides (callers check membership)
        pkg, sym = target.split(":", 1)
        return f"{pkg}.{sym}"


class Project:
    """Every parsed module under one (or more) roots."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: Dict[str, Module] = {}

    @classmethod
    def load(cls, root: str,
             package: str = "libskylark_tpu") -> "Project":
        proj = cls(root)
        pkg_dir = os.path.join(proj.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                proj.add_file(path)
        return proj

    def add_file(self, path: str) -> Module:
        rel = os.path.relpath(os.path.abspath(path),
                              self.root).replace(os.sep, "/")
        modname = rel[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        mod = Module(rel, modname, source)
        self.modules[modname] = mod
        return mod

    def module_for(self, dotted: str) -> Optional[Module]:
        return self.modules.get(dotted)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

_RULES: Dict[str, Callable[[Project], List[Finding]]] = {}
_RULE_DOCS: Dict[str, str] = {}


def rule(name: str, doc: str = ""):
    """Register a rule: a callable ``(Project) -> list[Finding]``."""

    def deco(fn):
        _RULES[name] = fn
        _RULE_DOCS[name] = doc or (fn.__doc__ or "").strip()
        return fn

    return deco


def registered_rules() -> Dict[str, str]:
    _ensure_rules_loaded()
    return dict(_RULE_DOCS)


def _ensure_rules_loaded() -> None:
    # rule modules self-register on import
    from libskylark_tpu.analysis import rules  # noqa: F401


def run_rules(project: Project,
              only: Optional[List[str]] = None) -> List[Finding]:
    """Run every (or the selected) registered rule; suppressed
    findings are dropped here, centrally."""
    _ensure_rules_loaded()
    findings: List[Finding] = []
    for name, fn in sorted(_RULES.items()):
        if only and name not in only:
            continue
        for f in fn(project):
            mod = next((m for m in project.modules.values()
                        if m.relpath == f.path), None)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def baseline_load(path: str = BASELINE_PATH) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return list(doc.get("findings", []))


def baseline_save(findings: List[Finding],
                  path: str = BASELINE_PATH) -> None:
    doc = {
        "comment": (
            "Grandfathered skylark-lint findings. SHRINK-ONLY: fix a "
            "finding, delete its entry. The gate fails on findings "
            "missing here (new debt) and on entries matching nothing "
            "(stale debt)."),
        "findings": [
            {"rule": f.rule, "path": f.path, "symbol": f.symbol,
             "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")


def compare_to_baseline(
        findings: List[Finding],
        path: str = BASELINE_PATH) -> Tuple[List[Finding], List[dict]]:
    """(new findings not in the baseline, stale baseline entries
    matching no current finding). Both must be empty for the gate."""
    base = baseline_load(path)
    base_keys = {(b["rule"], b["path"], b["symbol"], b["message"])
                 for b in base}
    current_keys = {f.key() for f in findings}
    new = [f for f in findings if f.key() not in base_keys]
    stale = [b for b in base
             if (b["rule"], b["path"], b["symbol"], b["message"])
             not in current_keys]
    return new, stale
