"""Rule modules self-register with :func:`..core.rule` on import."""

from libskylark_tpu.analysis.rules import (  # noqa: F401
    env_registry, jit_purity, lock_discipline, metric_names,
)
