"""``env-registry`` — every ``SKYLARK_*`` environment read goes
through the typed registry in ``base/env.py``.

Motivating bug class (r13): a process replica booted with whatever
``os.environ`` happened to hold at ``Process.start()`` — a variable
read raw somewhere could silently disagree between parent and child
because nothing forced it into the propagation snapshot. With the
registry, the declaration *is* the propagation decision, so the rule
reduces the invariant to "no reads outside the registry":

- ``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv(...)`` /
  ``"SKYLARK_X" in os.environ`` with a ``SKYLARK_*`` literal, anywhere
  but ``base/env.py`` → finding;
- any env read with a **non-literal** key (it could hide a SKYLARK
  read) → finding;
- any ``SKYLARK_[A-Z0-9_]+`` token in a non-docstring string constant
  that is not a declared variable name → finding (catches typos and
  undeclared-but-referenced vars);
- a duplicate ``declare()`` would raise at import; the rule also flags
  ``declare()`` calls outside ``base/env.py``.

Writes (``os.environ[k] = v``, ``.pop``, ``.setdefault``) and whole-
environment snapshots (``dict(os.environ)``) are allowed — the replica
apply path and subprocess spawns need them; only *reads of specific
keys* route through the registry.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from libskylark_tpu.analysis.core import Finding, Project, rule

ENV_MODULE = "libskylark_tpu.base.env"
_TOKEN_RE = re.compile(r"SKYLARK_[A-Z0-9_]+")


def declared_names(project: Project) -> Set[str]:
    """Variable names declared in base/env.py, extracted from its AST
    (no runtime import — the lint must run on a broken tree too)."""
    mod = project.module_for(ENV_MODULE)
    if mod is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "declare"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
    return out


def _is_os_environ(node: ast.AST, mod) -> bool:
    """``os.environ`` (or an alias of the os module).environ."""
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and mod.resolve_alias_module(node.value.id) == "os")


def _docstring_positions(tree: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                c = body[0].value
                for ln in range(c.lineno, (c.end_lineno or c.lineno) + 1):
                    out.add(ln)
    return out


@rule("env-registry",
      "SKYLARK_* env reads must go through base/env.py; referenced "
      "names must be declared there")
def check(project: Project) -> List[Finding]:
    declared = declared_names(project)
    findings: List[Finding] = []

    for mod in project.modules.values():
        if mod.modname == ENV_MODULE:
            continue
        doclines = _docstring_positions(mod.tree)
        for node in ast.walk(mod.tree):
            # -- raw reads ------------------------------------------------
            key_node = None
            form = None
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _is_os_environ(node.value, mod)):
                key_node, form = node.slice, "os.environ[...]"
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in ("get",)
                        and _is_os_environ(f.value, mod)):
                    key_node = node.args[0] if node.args else None
                    form = "os.environ.get(...)"
                elif (isinstance(f, ast.Attribute)
                        and f.attr == "getenv"
                        and isinstance(f.value, ast.Name)
                        and mod.resolve_alias_module(f.value.id) == "os"):
                    key_node = node.args[0] if node.args else None
                    form = "os.getenv(...)"
                elif (isinstance(f, ast.Name) and f.id == "declare"
                        and mod.import_aliases.get("declare", "")
                        .startswith(ENV_MODULE)):
                    findings.append(Finding(
                        "env-registry", mod.relpath, node.lineno,
                        "declare",
                        "declare() outside base/env.py — declarations "
                        "live in the registry module only"))
            elif (isinstance(node, ast.Compare)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and _is_os_environ(node.comparators[0], mod)):
                key_node, form = node.left, "... in os.environ"

            if form is not None:
                if (isinstance(key_node, ast.Constant)
                        and isinstance(key_node.value, str)):
                    key = key_node.value
                    if key.startswith("SKYLARK_"):
                        findings.append(Finding(
                            "env-registry", mod.relpath, node.lineno,
                            key,
                            f"raw {form} read of {key} — use the "
                            f"base/env.py registry accessor"))
                else:
                    findings.append(Finding(
                        "env-registry", mod.relpath, node.lineno,
                        "<dynamic>",
                        f"{form} with a non-literal key — could hide "
                        f"a SKYLARK_* read; use base/env.py"))

            # -- undeclared names in string constants --------------------
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.lineno not in doclines):
                for token in _TOKEN_RE.findall(node.value):
                    tok = token.rstrip("_")
                    if tok not in declared and tok != "SKYLARK_":
                        findings.append(Finding(
                            "env-registry", mod.relpath, node.lineno,
                            tok,
                            f"references undeclared environment "
                            f"variable {tok} — declare it in "
                            f"base/env.py"))
    return findings
