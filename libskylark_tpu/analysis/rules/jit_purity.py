"""``jit-purity`` — traced functions must be pure.

Motivating bug class: the jit-leak CI gate catches a *flapping cache
key* only after it has thrashed the executable cache at runtime; a
tracer that reads ``os.environ``, a wall clock, host RNG, or a mutable
module global bakes a trace-time value into the compiled program — the
executable silently disagrees with the environment the next process
(or the next minute) runs in, and nothing invalidates it.

Roots (the functions whose bodies trace):

- functions decorated with ``jax.jit`` / ``@compiled`` /
  ``@engine_compile`` (any alias of ``engine.compiled.compiled``,
  including ``functools.partial(jax.jit, ...)`` decorators);
- functions passed as the first argument to ``jax.jit(...)`` /
  ``compiled(...)`` / ``engine_compile(...)`` — the serve layer's
  flush-builder idiom (``_build_batched`` returns
  ``engine_compile(inner_fn, ...)``).

From each root the rule follows the project call graph (conservative:
unresolved calls contribute nothing) and flags every reachable
impurity:

- ``os.environ`` / ``os.getenv`` / ``base.env`` registry reads;
- wall clocks: ``time.time/monotonic/perf_counter/time_ns``,
  ``datetime.now/utcnow``;
- host RNG: the stdlib ``random`` module, ``np.random``;
- reads of mutable module globals — names rebound via ``global``
  somewhere in their module (the set-at-runtime knob pattern).

A *deliberate* trace-time read (a precision policy resolved at trace
time and captured in the cache key) is suppressed **at the impure
line** with ``# skylark-lint: disable=jit-purity`` plus a comment
saying why the key covers it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from libskylark_tpu.analysis.callgraph import CallGraph, iter_own_nodes
from libskylark_tpu.analysis.core import Finding, Project, rule

RULE = "jit-purity"

ENV_MODULE = "libskylark_tpu.base.env"
_COMPILE_WRAPPERS = {"libskylark_tpu.engine.compiled:compiled"}
_CLOCK_ATTRS = {"time", "monotonic", "perf_counter", "time_ns",
                "monotonic_ns", "perf_counter_ns"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}


def _dotted(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _ModuleFacts:
    """Per-module context shared by root + impurity detection."""

    def __init__(self, mod):
        self.mod = mod
        # names rebound via ``global`` in any function of the module
        self.mutable_globals: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Global):
                self.mutable_globals.update(node.names)

    def alias_of(self, name: str) -> Optional[str]:
        return self.mod.resolve_alias_module(name)

    def is_jit_attr(self, node: ast.AST) -> bool:
        """``jax.jit`` / ``jit`` imported from jax."""
        d = _dotted(node)
        if not d:
            return False
        if d[-1] != "jit":
            return False
        if len(d) == 1:
            return self.mod.import_aliases.get("jit", "") == "jax:jit"
        return self.alias_of(d[0]) == "jax"

    def is_compile_wrapper(self, node: ast.AST) -> bool:
        """Any alias of engine.compiled.compiled (``compiled``,
        ``engine_compile``, ``engine.compiled.compiled``...)."""
        if isinstance(node, ast.Name):
            return (self.mod.import_aliases.get(node.id, "")
                    in {w.replace(":", ":") for w in _COMPILE_WRAPPERS}
                    or self.mod.import_aliases.get(node.id, "")
                    == "libskylark_tpu.engine.compiled:compiled")
        d = _dotted(node)
        if d and d[-1] == "compiled" and len(d) >= 2:
            target = self.alias_of(d[0])
            if target and "engine" in target:
                return True
        return False


def _direct_impurities(graph: CallGraph,
                       facts: Dict[str, _ModuleFacts]
                       ) -> Dict[str, Set[Tuple[str, str, int]]]:
    """qualname -> {(kind, detail, lineno)} of impurities written
    directly in that function's own body (suppressed lines skipped)."""
    out: Dict[str, Set[Tuple[str, str, int]]] = {}
    for qn, fn in graph.functions.items():
        mod = fn.module
        mf = facts[mod.modname]
        found: Set[Tuple[str, str, int]] = set()

        def note(kind, detail, lineno):
            if not mod.is_suppressed(RULE, lineno):
                found.add((kind, detail, lineno))

        for node in iter_own_nodes(fn.node, ast.AST):
            d = _dotted(node) if isinstance(node, ast.Attribute) else None
            if d:
                root_target = mf.alias_of(d[0])
                # os.environ / os.getenv
                if root_target == "os" and len(d) >= 2 and d[1] in (
                        "environ", "getenv"):
                    note("env", ".".join(d[:2]), node.lineno)
                # base.env registry access
                elif root_target == ENV_MODULE and len(d) >= 2:
                    note("env", f"base.env.{d[1]}", node.lineno)
                # clocks
                elif (root_target == "time" and len(d) == 2
                        and d[1] in _CLOCK_ATTRS):
                    note("clock", ".".join(d), node.lineno)
                elif (root_target == "datetime" and d[-1]
                        in _DATETIME_ATTRS):
                    note("clock", ".".join(d), node.lineno)
                # host RNG
                elif root_target == "random" and len(d) >= 2:
                    note("host-rng", ".".join(d[:2]), node.lineno)
                elif (root_target in ("numpy", "np")
                        and len(d) >= 2 and d[1] == "random"):
                    note("host-rng", ".".join(d[:2]), node.lineno)
                elif (root_target == "numpy.random"):
                    note("host-rng", "numpy.random", node.lineno)
            elif (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in mf.mutable_globals):
                # reading a module global some function rebinds
                note("mutable-global",
                     f"{mod.modname}:{node.id}", node.lineno)
        if found:
            out[qn] = found
    return out


def _roots(graph: CallGraph,
           facts: Dict[str, _ModuleFacts]) -> Dict[str, int]:
    """qualname -> lineno of every jit/compile root."""
    roots: Dict[str, int] = {}
    for qn, fn in graph.functions.items():
        mf = facts[fn.module.modname]
        for deco in getattr(fn.node, "decorator_list", []):
            target = deco.func if isinstance(deco, ast.Call) else deco
            if mf.is_jit_attr(target) or mf.is_compile_wrapper(target):
                roots[qn] = fn.node.lineno
            elif (isinstance(deco, ast.Call)
                    and _dotted(deco.func)
                    and _dotted(deco.func)[-1] == "partial"
                    and deco.args
                    and (mf.is_jit_attr(deco.args[0])
                         or mf.is_compile_wrapper(deco.args[0]))):
                roots[qn] = fn.node.lineno
    # call-form roots: jax.jit(f) / compiled(f) / engine_compile(f),
    # inside functions (full scope resolution) ...
    for qn, fn in graph.functions.items():
        mf = facts[fn.module.modname]
        for call in iter_own_nodes(fn.node, ast.Call):
            if not (mf.is_jit_attr(call.func)
                    or mf.is_compile_wrapper(call.func)):
                continue
            if not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Name):
                callee = graph._resolve_name(fn.module, fn, arg.id)
                if callee and callee not in roots:
                    roots[callee] = graph.functions[callee].node.lineno
    # ... and at module level (``_svd_compiled = engine.compiled(fn,
    # ...)`` — the solver-module idiom), resolving against top-level
    # function names only
    for mod in (fn.module for fn in graph.functions.values()):
        mf = facts[mod.modname]
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            if not (mf.is_jit_attr(call.func)
                    or mf.is_compile_wrapper(call.func)):
                continue
            if not (call.args and isinstance(call.args[0], ast.Name)):
                continue
            callee = f"{mod.modname}:{call.args[0].id}"
            if callee in graph.functions and callee not in roots:
                roots[callee] = graph.functions[callee].node.lineno
    return roots


@rule(RULE,
      "functions reaching jax.jit/engine.compiled must not read env, "
      "clocks, host RNG, or mutable module globals")
def check(project: Project) -> List[Finding]:
    graph = CallGraph(project)
    facts = {m.modname: _ModuleFacts(m)
             for m in project.modules.values()}
    direct = _direct_impurities(graph, facts)
    trans = graph.propagate(direct)
    findings: List[Finding] = []
    for qn, lineno in sorted(_roots(graph, facts).items()):
        fn = graph.functions[qn]
        for kind, detail in sorted({(k, d)
                                    for k, d, _ in trans.get(qn, ())}):
            findings.append(Finding(
                RULE, fn.module.relpath, lineno, qn,
                f"traced root reaches {kind} impurity ({detail})"))
    return findings
