"""``lock-discipline`` — the static lock-order graph stays acyclic and
nothing blocking runs under a held lock.

Motivating bug class (r9): the SIGTERM handler once drained an
executor while a flush worker held a lock the drain needed — a
deadlock that only manifests under the right interleaving. The
*ordering* both code paths exhibit on every run is statically visible;
this rule derives it from the AST over the same ``base.locks`` site
names the runtime witness records, so the two graphs are directly
comparable (the chaos battery validates them against each other).

Checks:

1. **lock naming** — ``threading.Lock()`` / ``RLock()`` / a bare
   ``Condition()`` constructed anywhere but ``base/locks.py``: use
   ``base.locks.make_lock(<site name>)`` so both graphs see the site
   (``Condition(existing_lock)`` is fine — it aliases a named lock).
2. **order cycles** — an edge A → B is recorded when B's site is
   acquired (directly, or transitively through resolvable calls) in a
   ``with A:`` body. Any cycle in the resulting graph is a finding.
3. **blocking under a lock** — in a ``with <lock>:`` body:
   ``Future.result()`` / ``.join()`` / ``time.sleep`` / pipe
   ``.recv()`` / ``.wait()`` on anything that is not a Condition over
   the held lock; plus callback fan-out (calling a loop variable —
   the subscriber-list pattern) and inline future resolution
   (``set_result`` / ``set_exception`` / ``add_done_callback`` run
   arbitrary client callbacks on this thread, under the lock).

The graph is exported for ``script/lint --graph`` via
:func:`static_lock_graph`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from libskylark_tpu.analysis.callgraph import CallGraph
from libskylark_tpu.analysis.core import Finding, Module, Project, rule

RULE = "lock-discipline"
LOCKS_MODULE = "libskylark_tpu.base.locks"
_FACTORIES = ("make_lock", "make_rlock")


class LockIndex:
    """Where every named lock lives: module globals, class attributes,
    function locals — plus Condition aliases onto them."""

    def __init__(self, project: Project):
        self.project = project
        # (modname, scope, varname) -> site name;  scope is "" for
        # module level, the class name for attributes, the function
        # qualpath for locals
        self.slots: Dict[Tuple[str, str, str], str] = {}
        for mod in project.modules.values():
            self._index(mod)
        # second pass: Condition aliases resolve against known slots
        for mod in project.modules.values():
            self._index_conditions(mod)

    def _factory_site(self, mod: Module,
                      call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        f = call.func
        ok = False
        if isinstance(f, ast.Attribute) and f.attr in _FACTORIES:
            if (isinstance(f.value, ast.Name)
                    and mod.resolve_alias_module(f.value.id)
                    == LOCKS_MODULE):
                ok = True
        elif isinstance(f, ast.Name) and f.id in _FACTORIES:
            ok = (mod.import_aliases.get(f.id, "").split(":")[0]
                  == LOCKS_MODULE)
        if not ok:
            return None
        if (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return call.args[0].value
        return "<unnamed>"

    def _walk_scopes(self, mod: Module):
        """Yield (scope, class_name, assign-node) for every Assign,
        tracking the lexical scope it executes in."""

        def visit(node, scope: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    yield from visit(child, child.name, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fscope = (f"{scope}.{child.name}" if scope
                              else child.name)
                    yield from visit(child, fscope, cls)
                else:
                    if isinstance(child, ast.Assign):
                        yield (scope, cls, child)
                    yield from visit(child, scope, cls)

        yield from visit(mod.tree, "", None)

    def _slot_for_target(self, mod: Module, scope: str,
                         cls: Optional[str],
                         target: ast.AST) -> Optional[Tuple]:
        if isinstance(target, ast.Name):
            key_scope = "" if scope == "" else scope
            return (mod.modname, key_scope, target.id)
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and cls):
            return (mod.modname, f"class:{cls}", target.attr)
        return None

    def _index(self, mod: Module) -> None:
        for scope, cls, assign in self._walk_scopes(mod):
            site = self._factory_site(mod, assign.value)
            if site is None:
                continue
            for t in assign.targets:
                slot = self._slot_for_target(mod, scope, cls, t)
                if slot:
                    self.slots[slot] = site

    def _index_conditions(self, mod: Module) -> None:
        for scope, cls, assign in self._walk_scopes(mod):
            v = assign.value
            if not (isinstance(v, ast.Call) and v.args):
                continue
            f = v.func
            is_cond = ((isinstance(f, ast.Attribute)
                        and f.attr == "Condition")
                       or (isinstance(f, ast.Name)
                           and f.id == "Condition"))
            if not is_cond:
                continue
            inner = self.resolve(mod, scope, cls, v.args[0])
            if inner is None:
                continue
            for t in assign.targets:
                slot = self._slot_for_target(mod, scope, cls, t)
                if slot:
                    self.slots[slot] = inner

    def resolve(self, mod: Module, scope: str, cls: Optional[str],
                expr: ast.AST) -> Optional[str]:
        """Site name of a lock expression in the given scope."""
        if isinstance(expr, ast.Name):
            # function local (any enclosing function scope), else
            # module global
            parts = scope.split(".") if scope else []
            for cut in range(len(parts), 0, -1):
                hit = self.slots.get(
                    (mod.modname, ".".join(parts[:cut]), expr.id))
                if hit:
                    return hit
            return self.slots.get((mod.modname, "", expr.id))
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            if expr.value.id == "self" and cls:
                return self.slots.get(
                    (mod.modname, f"class:{cls}", expr.attr))
            target = mod.resolve_alias_module(expr.value.id)
            if target:
                return self.slots.get((target, "", expr.attr))
        return None

    def is_condition_expr(self, mod: Module, scope: str,
                          cls: Optional[str], expr: ast.AST) -> bool:
        """Whether expr resolves through a Condition alias slot (its
        ``.wait()`` releases the lock — not a blocking violation)."""
        # conditions were folded into slots with their lock's name, so
        # any resolvable slot is either the lock or a condition on it;
        # for the blocking check both are acceptable wait targets.
        return self.resolve(mod, scope, cls, expr) is not None


# ---------------------------------------------------------------------------


def _fn_scope(qualname: str) -> str:
    """callgraph qualpath -> LockIndex scope string."""
    return qualname.split(":", 1)[1].replace(".<locals>", "")


def _analyze_function(graph: CallGraph, index: LockIndex, qn: str):
    """(direct-acquires, edges, calls-under-lock, blocking-findings)
    for one function."""
    fn = graph.functions[qn]
    mod = fn.module
    scope = _fn_scope(qn)
    cls = fn.cls
    acquires: Set[str] = set()
    edges: List[Tuple[str, str, int]] = []
    calls_under: List[Tuple[Tuple[str, ...], str, int]] = []
    blocking: List[Tuple[str, str, int]] = []

    def visit(node, held: Tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, ast.With):
            new = list(held)
            for item in node.items:
                site = index.resolve(mod, scope, cls, item.context_expr)
                if site:
                    acquires.add(site)
                    for h in new:
                        if h != site:
                            edges.append((h, site, node.lineno))
                    new.append(site)
            for child in node.body:
                visit(child, tuple(new))
            return
        if isinstance(node, ast.Call):
            f = node.func
            callee = graph.resolve_call(mod, fn, node)
            if held:
                if callee:
                    calls_under.append((held, callee, node.lineno))
                _check_blocking(node, f, held)
            # loop-variable callback fan-out handled via _check_blocking
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    loop_vars: Set[str] = set()

    def collect_loop_vars(node):
        for n in ast.walk(node):
            if isinstance(n, ast.For) and isinstance(n.target, ast.Name):
                loop_vars.add(n.target.id)

    collect_loop_vars(fn.node)

    def _check_blocking(call: ast.Call, f, held):
        desc = None
        if isinstance(f, ast.Attribute):
            if f.attr == "result":
                desc = "Future.result()"
            elif (f.attr == "join"
                    and not isinstance(f.value, ast.Constant)):
                desc = ".join()"
            elif f.attr == "recv":
                desc = "pipe .recv()"
            elif (f.attr == "wait"
                    and not index.is_condition_expr(mod, scope, cls,
                                                    f.value)):
                desc = ".wait() on a non-condition"
            elif f.attr == "sleep" and isinstance(f.value, ast.Name) \
                    and mod.resolve_alias_module(f.value.id) == "time":
                desc = "time.sleep()"
            elif f.attr in ("set_result", "set_exception",
                            "add_done_callback"):
                desc = f"Future.{f.attr}() (runs done-callbacks inline)"
        elif isinstance(f, ast.Name) and f.id in loop_vars:
            desc = f"callback fan-out ({f.id}(...) from a loop)"
        if desc:
            blocking.append((held[-1], desc, call.lineno))

    for stmt in fn.node.body:
        visit(stmt, ())
    return acquires, edges, calls_under, blocking


def static_lock_graph(project: Project) -> Dict[str, object]:
    """The derived graph: ``{"edges": {A: [B...]}, "sites": [...]}`` —
    the static counterpart of ``base.locks.witness_report()``."""
    graph = CallGraph(project)
    index = LockIndex(project)
    direct_acq: Dict[str, Set[str]] = {}
    all_edges: Dict[str, Set[str]] = {}
    edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    calls_under_all = []
    blocking_all = []
    for qn in graph.functions:
        acq, edges, calls_under, blocking = _analyze_function(
            graph, index, qn)
        direct_acq[qn] = acq
        for a, b, ln in edges:
            all_edges.setdefault(a, set()).add(b)
            edge_sites.setdefault(
                (a, b), (graph.functions[qn].module.relpath, ln))
        calls_under_all.append((qn, calls_under))
        blocking_all.append((qn, blocking))
    # transitive: a call made under lock H reaches everything the
    # callee (transitively) acquires
    trans_acq = graph.propagate(direct_acq)
    for qn, calls_under in calls_under_all:
        for held, callee, ln in calls_under:
            for b in trans_acq.get(callee, ()):
                for h in held:
                    if h != b:
                        all_edges.setdefault(h, set()).add(b)
                        edge_sites.setdefault(
                            (h, b),
                            (graph.functions[qn].module.relpath, ln))
    return {
        "edges": {a: sorted(bs) for a, bs in sorted(all_edges.items())},
        "edge_sites": edge_sites,
        "sites": sorted({s for s in (set(all_edges)
                                     | {b for bs in all_edges.values()
                                        for b in bs})}),
        "blocking": blocking_all,
    }


def _find_cycles(edges: Dict[str, List[str]]) -> List[List[str]]:
    cycles: List[List[str]] = []
    seen_cycle_keys: Set[Tuple[str, ...]] = set()

    def dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in edges.get(node, ()):
                if nxt == start:
                    cyc = path[:]
                    key = tuple(sorted(cyc))
                    if key not in seen_cycle_keys:
                        seen_cycle_keys.add(key)
                        cycles.append(cyc + [start])
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))

    for a in edges:
        dfs(a)
    return cycles


@rule(RULE,
      "static lock-order graph acyclic; no blocking calls, callback "
      "fan-outs, or direct threading.Lock() under/outside base.locks")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    # 1. direct lock construction outside base/locks.py
    for mod in project.modules.values():
        if mod.modname == LOCKS_MODULE:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = None
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and mod.resolve_alias_module(f.value.id)
                    == "threading"
                    and f.attr in ("Lock", "RLock")):
                name = f"threading.{f.attr}"
            elif (isinstance(f, ast.Attribute) and f.attr == "Condition"
                    and not node.args
                    and isinstance(f.value, ast.Name)
                    and mod.resolve_alias_module(f.value.id)
                    == "threading"):
                name = "threading.Condition (bare: hidden RLock)"
            if name:
                findings.append(Finding(
                    RULE, mod.relpath, node.lineno, name,
                    f"direct {name}() — construct through "
                    f"base.locks.make_lock(<site>) so the witness and "
                    f"the static graph see the site"))

    g = static_lock_graph(project)

    # 2. cycles
    graph_obj = CallGraph(project)  # for relpaths in findings
    for cyc in _find_cycles(g["edges"]):
        desc = " -> ".join(cyc)
        a, b = cyc[0], cyc[1]
        relpath, ln = g["edge_sites"].get((a, b), ("<unknown>", 1))
        findings.append(Finding(
            RULE, relpath, ln, f"cycle:{'|'.join(sorted(set(cyc)))}",
            f"lock-order cycle {desc} — two paths take these sites in "
            f"opposite orders"))

    # 3. blocking under a held lock
    for qn, blocking in g["blocking"]:
        fn = graph_obj.functions.get(qn)
        if fn is None:
            continue
        for held, desc, ln in blocking:
            if fn.module.is_suppressed(RULE, ln):
                continue
            findings.append(Finding(
                RULE, fn.module.relpath, ln, qn,
                f"{desc} while holding lock {held!r}"))
    return findings
