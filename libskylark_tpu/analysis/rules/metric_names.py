"""``metric-names`` — every telemetry instrument is declared once.

The unified registry (r10) made metric *plumbing* uniform; the names
stayed convention. This rule makes the convention checkable against
:mod:`libskylark_tpu.telemetry.names`:

- a ``counter("x")`` / ``gauge("x")`` / ``histogram("x")`` creation
  whose name is not declared → finding;
- a declared name created at more than one call site → finding (two
  sites silently share one instrument, or disagree on kind and raise);
- a creation whose kind differs from the declaration → finding;
- a declaration with no creation site → finding (stale — delete it);
- a name that would not render as a valid Prometheus metric after the
  exporter's ``.`` → ``_`` mapping → finding;
- a non-literal name argument → finding (unauditable).

Creation sites are calls ``<telemetry alias>.counter/gauge/histogram``
(``_metrics``, ``_telemetry``, ... — any alias resolving to
``libskylark_tpu.telemetry`` or ``.telemetry.metrics``) or the bare
names imported from there. The registry's own module and the names
module are exempt (definitions, not uses).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from libskylark_tpu.analysis.core import Finding, Project, rule

NAMES_MODULE = "libskylark_tpu.telemetry.names"
_EXEMPT = ("libskylark_tpu.telemetry.metrics", NAMES_MODULE)
_TELEMETRY_MODULES = ("libskylark_tpu.telemetry",
                      "libskylark_tpu.telemetry.metrics")
_KINDS = ("counter", "gauge", "histogram")
# the exporter maps "." to "_"; everything else must conform already
_PROM_OK = re.compile(r"^[a-z][a-z0-9_.]*$")


def declared_metrics(project: Project) -> Dict[str, str]:
    """The METRICS dict from telemetry/names.py, via AST."""
    mod = project.module_for(NAMES_MODULE)
    if mod is None:
        return {}
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.target.id == "METRICS"
                and isinstance(node.value, ast.Dict)):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant)
                        and isinstance(v, ast.Constant)):
                    out[k.value] = v.value
            return out
    return {}


def _creation_sites(project: Project) -> List[Tuple[str, object, str, object]]:
    """(kind, name-node-or-None, relpath, call-node) for every
    instrument creation call outside the exempt modules."""
    sites = []
    for mod in project.modules.values():
        if mod.modname in _EXEMPT:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            kind = None
            if (isinstance(f, ast.Attribute) and f.attr in _KINDS
                    and isinstance(f.value, ast.Name)
                    and mod.resolve_alias_module(f.value.id)
                    in _TELEMETRY_MODULES):
                kind = f.attr
            elif isinstance(f, ast.Name) and f.id in _KINDS:
                target = mod.import_aliases.get(f.id, "")
                if target.split(":")[0] in _TELEMETRY_MODULES:
                    kind = f.id
            if kind is None:
                continue
            name_node = node.args[0] if node.args else None
            sites.append((kind, name_node, mod.relpath, node))
    return sites


@rule("metric-names",
      "telemetry instrument names are declared once in "
      "telemetry/names.py, Prometheus-conformant")
def check(project: Project) -> List[Finding]:
    declared = declared_metrics(project)
    findings: List[Finding] = []
    created: Dict[str, List[Tuple[str, int]]] = {}

    for kind, name_node, relpath, call in _creation_sites(project):
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            findings.append(Finding(
                "metric-names", relpath, call.lineno, "<dynamic>",
                f"{kind}() with a non-literal name — metric names "
                f"must be auditable string literals"))
            continue
        name = name_node.value
        created.setdefault(name, []).append((relpath, call.lineno))
        if name not in declared:
            findings.append(Finding(
                "metric-names", relpath, call.lineno, name,
                f"metric {name!r} is not declared in "
                f"telemetry/names.py"))
        elif declared[name] != kind:
            findings.append(Finding(
                "metric-names", relpath, call.lineno, name,
                f"metric {name!r} created as {kind} but declared as "
                f"{declared[name]}"))
        if not _PROM_OK.match(name):
            findings.append(Finding(
                "metric-names", relpath, call.lineno, name,
                f"metric name {name!r} cannot render as a Prometheus "
                f"metric (want ^[a-z][a-z0-9_.]*$)"))

    for name, sites in created.items():
        if len(sites) > 1:
            where = ", ".join(f"{p}:{ln}" for p, ln in sites)
            findings.append(Finding(
                "metric-names", sites[1][0], sites[1][1], name,
                f"metric {name!r} created at {len(sites)} sites "
                f"({where}) — declare and create once"))

    names_mod = project.module_for(NAMES_MODULE)
    if names_mod is not None:
        for name in declared:
            if name not in created:
                findings.append(Finding(
                    "metric-names", names_mod.relpath, 1, name,
                    f"declared metric {name!r} has no creation site — "
                    f"stale declaration, delete it"))
    return findings
