"""Runtime foundation: context/RNG, params, errors, sparse containers.

TPU-native analog of the reference's ``base/`` layer (SURVEY.md §2.1).
"""

from libskylark_tpu.base.context import Allocation, Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.base.sparse import SparseMatrix, gemm, spmm, spmm_t
from libskylark_tpu.base.dist_sparse import DistSparseMatrix, distribute_sparse
from libskylark_tpu.base import errors, randgen, quasirand, sprand

__all__ = [
    "Allocation", "Context", "Params", "SparseMatrix",
    "DistSparseMatrix", "distribute_sparse",
    "gemm", "spmm", "spmm_t", "errors", "randgen", "quasirand", "sprand",
]
