"""Version-portability shims for jax APIs that moved between releases.

One import seam per moved symbol, so every caller in the package (and in
tests/) tracks a single definition instead of each picking its own jax
version to support. The rule for adding a shim: prefer the NEWEST public
location first, fall back to where older installed versions keep it, and
raise the original ImportError only when no location works — the package
must import (and its CPU test tier must collect) on every jax the image
ships.

``shard_map``: public top-level ``jax.shard_map`` from jax 0.6; on the
0.4.x line it lives in ``jax.experimental.shard_map``. The replication
checker was also renamed across that move (``check_rep`` →
``check_vma``): the wrapper translates whichever spelling the call site
used into the one the installed jax accepts.

``pvary``: the varying-manual-axes annotation only exists on jax lines
that HAVE the vma system (as ``lax.pcast``/``lax.pvary``); where it
doesn't exist the annotation is meaningless and the shim is identity.
"""

from __future__ import annotations

import inspect

from jax import lax

try:  # jax >= 0.6: public top-level export
    from jax import shard_map as _shard_map_impl
except ImportError:  # 0.4.x/0.5.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, **kwargs):
    """``shard_map`` accepting either replication-checker spelling
    (``check_vma``/``check_rep``) on any supported jax."""
    for ours, theirs in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _SHARD_MAP_PARAMS:
            if theirs in _SHARD_MAP_PARAMS:
                kwargs[theirs] = kwargs.pop(ours)
            else:
                kwargs.pop(ours)
    return _shard_map_impl(f, **kwargs)


def pvary(x, axis_name):
    """Mark ``x`` device-varying over ``axis_name`` inside a manual
    (shard_map) region — identity on jax lines without the vma type
    system, where every value is already implicitly varying."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


__all__ = ["pvary", "shard_map"]
