"""Deterministic random context: global (seed, counter) state.

TPU-native analog of the reference's ``context_t`` (ref: base/context.hpp:19-194).
The reference hands out *counter ranges* of a virtual 2^64-long Threefry random
stream; any process can evaluate any element statelessly, which is what makes
sketches layout-independent and serializable.

``jax.random`` is itself a counter-based Threefry generator, so the mapping is
nearly 1:1 — but instead of a single flat 2^64 stream we hand out *allocation
subkeys*: allocation ``i`` of a context with seed ``s`` is the key
``fold_in(key(s), i)``. Within an allocation, element access is again a pure
function of (allocation key, element index) — see :mod:`libskylark_tpu.base.randgen`.
The (seed, counter) pair round-trips through JSON exactly like the reference's
ptree serialization (ref: base/context.hpp:86-98), and an allocation can be
reconstructed from (seed, counter) alone without the context object.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax
import jax.random as jr


@dataclasses.dataclass(frozen=True)
class Allocation:
    """A reserved slot of the context's random space.

    Reconstructible from (seed, counter) alone — this pair is what sketch
    transforms serialize as their ``creation_context``
    (ref: sketch/sketch_transform_data.hpp:64-71). ``path`` supports nested
    sub-allocations for compound transforms (e.g. PPT's internal CWTs): each
    element is folded into the key in order.
    """

    seed: int
    counter: int
    path: tuple = ()

    @property
    def key(self) -> jax.Array:
        k = jr.fold_in(jr.key(self.seed), self.counter)
        for p in self.path:
            k = jr.fold_in(k, p)
        return k

    def child(self, tag: int) -> "Allocation":
        return Allocation(self.seed, self.counter, self.path + (int(tag),))

    def to_dict(self) -> dict[str, Any]:
        d = {"seed": int(self.seed), "counter": int(self.counter)}
        if self.path:
            d["path"] = list(self.path)
        return d

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Allocation":
        return Allocation(
            int(d["seed"]), int(d["counter"]), tuple(d.get("path", ()))
        )


class Context:
    """Global deterministic RNG state = (seed, counter).

    ``allocate()`` reserves the next slot of the virtual random space and
    advances the counter (ref: base/context.hpp:130-137,
    ``allocate_random_samples_array``). Like the reference, allocation must be
    performed consistently across any cooperating processes to keep state
    synchronized — in JAX SPMD this is automatic because the context lives in
    the single Python program driving the mesh.
    """

    def __init__(self, seed: int = 0, counter: int = 0):
        self._seed = int(seed)
        self._counter = int(counter)

    @property
    def seed(self) -> int:
        return self._seed

    @property
    def counter(self) -> int:
        return self._counter

    def allocate(self) -> Allocation:
        """Reserve the next allocation slot; advances the counter."""
        alloc = Allocation(self._seed, self._counter)
        self._counter += 1
        return alloc

    def random_value(self, sampler, **kwargs):
        """Draw a single host-side sample (ref: base/context.hpp ``random_value``)."""
        alloc = self.allocate()
        return sampler(alloc.key, **kwargs)

    # -- serialization (ptree-compatible JSON; ref: base/context.hpp:86-98) --

    def to_dict(self) -> dict[str, Any]:
        return {
            "skylark_object_type": "context",
            "seed": self._seed,
            "counter": self._counter,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Context":
        return Context(int(d["seed"]), int(d.get("counter", 0)))

    @staticmethod
    def from_json(s: str) -> "Context":
        return Context.from_dict(json.loads(s))

    def __repr__(self) -> str:
        return f"Context(seed={self._seed}, counter={self._counter})"
