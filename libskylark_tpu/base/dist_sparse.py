"""Mesh-distributed sparse matrix: the P4/P5 parallelism strategies.

TPU-native analog of the reference's distributed sparse containers and
their sketch/gemm code paths:

- ``sparse_dist_matrix_t`` + VC★/★VR — a 1D-distributed sparse matrix with
  owner/local-index arithmetic (ref: base/sparse_dist_matrix.hpp:46-389,
  base/sparse_vc_star_matrix.hpp:19-52),
- the CombBLAS 2D SUMMA grid (SpParMat on a √p×√p grid) and the mixed
  CombBLAS×Elemental gemm bridges (ref: sketch/hash_transform_CombBLAS.hpp:
  16-632, base/detail/combblas_mixed_gemm.hpp:14-376).

Design (TPU-first, not a port): the nonzeros are partitioned by
(row-block × col-block) grid cell over a 1D or 2D mesh. Each cell stores
its triplets in *local* coordinates, zero-padded to one uniform nnz so the
whole matrix is three stacked device arrays of static shape
``(pr, pc, pad)`` — ``lr`` (local row), ``lc`` (local col), ``v`` (value;
0.0 for padding at local (0, 0)) — sharded
``NamedSharding(mesh, P(row_axis, col_axis, None))``. Row/col blocks are
``ceil(h/pr)`` / ``ceil(w/pc)`` wide; ragged edges are handled by the
uniform padded block size (the np∈{5,7} layouts the reference tests,
ref: tests/unit/CMakeLists.txt:31-33).

Products are ``shard_map`` local segment-sums + one ``psum`` over the
contracted mesh axis — the reference's local-gemm + all_reduce pattern
(ref: base/Gemm.hpp:84-103) with the SUMMA reduction riding ICI. Dense
operands enter sharded on the matching axis and zero-padded to the block
grid; outputs come back sharded on the kept axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libskylark_tpu.base import errors
from libskylark_tpu.base.compat import shard_map
from libskylark_tpu.base.sparse import SparseMatrix


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _pad_rows(B: jnp.ndarray, to: int) -> jnp.ndarray:
    return B if B.shape[0] == to else jnp.pad(B, ((0, to - B.shape[0]), (0, 0)))


class DistSparseMatrix:
    """Sparse (h × w) matrix distributed over a mesh grid (see module doc).

    Construct with :func:`distribute_sparse`; ``row_axis``/``col_axis`` are
    mesh axis names (either may be None for a 1D distribution — the VC★ /
    ★VR analogs; both set is the 2D SUMMA-grid analog, P4).
    """

    def __init__(
        self,
        mesh: Mesh,
        row_axis: Optional[str],
        col_axis: Optional[str],
        shape: Tuple[int, int],
        lr: jax.Array,
        lc: jax.Array,
        v: jax.Array,
    ):
        self.mesh = mesh
        self.row_axis = row_axis
        self.col_axis = col_axis
        self._shape = shape
        self.pr = mesh.shape[row_axis] if row_axis else 1
        self.pc = mesh.shape[col_axis] if col_axis else 1
        self.bs_r = _ceil_div(shape[0], self.pr)
        self.bs_c = _ceil_div(shape[1], self.pc)
        self.lr, self.lc, self.v = lr, lc, v

    # -- queries --

    @property
    def shape(self) -> Tuple[int, int]:
        return self._shape

    @property
    def height(self) -> int:
        return self._shape[0]

    @property
    def width(self) -> int:
        return self._shape[1]

    @property
    def dtype(self):
        return self.v.dtype

    def _spec(self, *dims) -> P:
        return P(*dims)

    def _triplet_spec(self) -> P:
        return P(self.row_axis, self.col_axis, None)

    def _axes(self):
        """(row axes present, col axes present) as psum-able names."""
        return self.row_axis, self.col_axis

    # -- conversions (tests / host interop) --

    def to_local(self) -> SparseMatrix:
        """Gather to a host-side local :class:`SparseMatrix` (the
        CIRC_CIRC analog)."""
        lr = np.asarray(jax.device_get(self.lr))
        lc = np.asarray(jax.device_get(self.lc))
        v = np.asarray(jax.device_get(self.v))
        rows = lr + (np.arange(self.pr) * self.bs_r)[:, None, None]
        cols = lc + (np.arange(self.pc) * self.bs_c)[None, :, None]
        rows = np.broadcast_to(rows, v.shape).reshape(-1)
        cols = np.broadcast_to(cols, v.shape).reshape(-1)
        vals = v.reshape(-1)
        keep = vals != 0
        return SparseMatrix.from_coo(
            rows[keep], cols[keep], vals[keep], self._shape
        )

    # -- products --

    def spmm(self, B) -> jax.Array:
        """A @ B, B dense (w, k) → (h, k) sharded on ``row_axis``.

        SUMMA over the col axis: each cell contracts its nonzeros against
        its B row-block locally (segment-sum over local rows), then one
        psum over ``col_axis`` (ref: base/Gemm.hpp:84-103 local+all_reduce;
        combblas_mixed_gemm.hpp SUMMA bridge)."""
        B = jnp.asarray(B)
        squeeze = B.ndim == 1
        if squeeze:
            B = B[:, None]
        if B.shape[0] != self.width:
            raise errors.InvalidParametersError(
                f"spmm: A is {self._shape}, B is {B.shape}"
            )
        B = _pad_rows(B, self.pc * self.bs_c).astype(self.v.dtype)
        k = B.shape[1]
        bs_r, bs_c = self.bs_r, self.bs_c
        col_axis, row_axis = self.col_axis, self.row_axis

        def local(lr, lc, v, B_loc):
            lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
            part = jax.ops.segment_sum(
                v[:, None] * B_loc[lc], lr, num_segments=bs_r
            )
            if col_axis:
                part = lax.psum(part, col_axis)
            return part[None]

        out = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                self._triplet_spec(),
                self._triplet_spec(),
                self._triplet_spec(),
                P(col_axis, None),
            ),
            out_specs=P(row_axis, None, None),
        )(self.lr, self.lc, self.v, B)
        out = out.reshape(self.pr * bs_r, k)[: self.height]
        return out[:, 0] if squeeze else out

    def spmm_t(self, B) -> jax.Array:
        """Aᵀ @ B, B dense (h, k) → (w, k) sharded on ``col_axis``
        (the Gram-type product; psum over ``row_axis``)."""
        B = jnp.asarray(B)
        squeeze = B.ndim == 1
        if squeeze:
            B = B[:, None]
        if B.shape[0] != self.height:
            raise errors.InvalidParametersError(
                f"spmm_t: A is {self._shape}, B is {B.shape}"
            )
        B = _pad_rows(B, self.pr * self.bs_r).astype(self.v.dtype)
        k = B.shape[1]
        bs_c = self.bs_c
        col_axis, row_axis = self.col_axis, self.row_axis

        def local(lr, lc, v, B_loc):
            lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
            part = jax.ops.segment_sum(
                v[:, None] * B_loc[lr], lc, num_segments=bs_c
            )
            if row_axis:
                part = lax.psum(part, row_axis)
            return part[None]

        out = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                self._triplet_spec(),
                self._triplet_spec(),
                self._triplet_spec(),
                P(row_axis, None),
            ),
            out_specs=P(col_axis, None, None),
        )(self.lr, self.lc, self.v, B)
        out = out.reshape(self.pc * bs_c, k)[: self.width]
        return out[:, 0] if squeeze else out

    def compact(self, utilization_threshold: float = 0.5
                ) -> "DistSparseMatrix":
        """Shrink the per-cell padding to the true max cell nnz when slot
        utilization has dropped below ``utilization_threshold``.

        Cell-merging operations (e.g. the sparse→sparse hash apply,
        sketch/dist_sparse_apply.py) multiply the padded slot count by the
        merged mesh-axis extent while the real nnz stays fixed, so chained
        applies compound mostly-zero slots that every downstream
        spmm/todense then segment-sums over. Compaction is device-side
        with a static output shape: one global-nnz readback picks the new
        pad, a per-cell stable argsort on the padding flag moves real
        entries first, and the slot axis is sliced. Entries with v == 0
        are semantically padding for every consumer (they contribute
        nothing to any product, the CSC duplicate-sum convention of
        ref: base/sparse_matrix.hpp:136), so dropping them is exact."""
        pad = self.v.shape[-1]
        true_pad = max(int(jnp.max(jnp.count_nonzero(self.v, axis=-1))), 1)
        if true_pad > pad * utilization_threshold:
            return self
        order = jnp.argsort((self.v == 0).astype(jnp.int32), axis=-1,
                            stable=True)[..., :true_pad]
        spec = NamedSharding(self.mesh, self._triplet_spec())
        take = lambda a: jax.device_put(
            jnp.take_along_axis(a, order, axis=-1), spec)
        return DistSparseMatrix(
            self.mesh, self.row_axis, self.col_axis, self._shape,
            take(self.lr), take(self.lc), take(self.v),
        )

    def transpose(self) -> "DistSparseMatrix":
        """Aᵀ — pure relabeling: swap the grid axes and the local
        coordinates (no data movement beyond the stacked-array transpose;
        ref: base/sparse_matrix.hpp Transpose:303)."""
        perm = (1, 0, 2)
        return DistSparseMatrix(
            self.mesh, self.col_axis, self.row_axis,
            (self.width, self.height),
            self.lc.transpose(perm), self.lr.transpose(perm),
            self.v.transpose(perm),
        )

    @property
    def T(self) -> "DistSparseMatrix":
        return self.transpose()

    def todense(self) -> jax.Array:
        """Dense (h, w) array sharded P(row_axis, col_axis)."""
        bs_r, bs_c = self.bs_r, self.bs_c

        def local(lr, lc, v):
            lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
            out = jnp.zeros((bs_r, bs_c), v.dtype).at[lr, lc].add(v)
            return out[None, None]

        out = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(self._triplet_spec(),) * 3,
            out_specs=P(self.row_axis, self.col_axis, None, None),
        )(self.lr, self.lc, self.v)
        out = out.transpose(0, 2, 1, 3).reshape(
            self.pr * bs_r, self.pc * bs_c
        )
        return out[: self.height, : self.width]

    def __repr__(self) -> str:
        return (
            f"DistSparseMatrix({self.height}x{self.width}, "
            f"grid={self.pr}x{self.pc}, pad_nnz={self.v.shape[-1]}, "
            f"axes=({self.row_axis}, {self.col_axis}))"
        )


def distribute_sparse(
    A: SparseMatrix,
    mesh: Mesh,
    row_axis: Optional[str] = None,
    col_axis: Optional[str] = None,
) -> DistSparseMatrix:
    """Partition a local :class:`SparseMatrix` onto the mesh grid.

    The analog of the reference's queue_update/finalize bulk construction
    (ref: base/sparse_dist_matrix.hpp:106-182): triplets are bucketed to
    their owner cell by index arithmetic, padded to a uniform per-cell nnz
    (pad entries: value 0 at local (0,0) — exact under every product), and
    shipped to devices as three stacked arrays.
    """
    if row_axis is None and col_axis is None:
        raise errors.InvalidParametersError(
            "distribute_sparse needs at least one mesh axis"
        )
    pr = mesh.shape[row_axis] if row_axis else 1
    pc = mesh.shape[col_axis] if col_axis else 1
    h, w = A.shape
    bs_r, bs_c = _ceil_div(h, pr), _ceil_div(w, pc)

    sp = A.to_scipy().tocoo()
    rows = np.asarray(sp.row, dtype=np.int64)
    cols = np.asarray(sp.col, dtype=np.int64)
    # device values follow the framework's precision policy (f64 host
    # buffers land as f32 — same as SparseMatrix.coo / the local oracle)
    vals = np.asarray(sp.data, dtype=np.dtype(A.device_dtype))
    rb, cb = rows // bs_r, cols // bs_c
    cell = rb * pc + cb
    order = np.argsort(cell, kind="stable")
    rows, cols, vals, cell = rows[order], cols[order], vals[order], cell[order]
    counts = np.bincount(cell, minlength=pr * pc)
    pad = max(int(counts.max()) if len(counts) else 0, 1)

    lr = np.zeros((pr, pc, pad), np.int32)
    lc = np.zeros((pr, pc, pad), np.int32)
    v = np.zeros((pr, pc, pad), vals.dtype)
    starts = np.concatenate([[0], np.cumsum(counts)])
    for cidx in range(pr * pc):
        s, e = starts[cidx], starts[cidx + 1]
        i, j = cidx // pc, cidx % pc
        lr[i, j, : e - s] = rows[s:e] - i * bs_r
        lc[i, j, : e - s] = cols[s:e] - j * bs_c
        v[i, j, : e - s] = vals[s:e]

    spec = NamedSharding(mesh, P(row_axis, col_axis, None))
    return DistSparseMatrix(
        mesh, row_axis, col_axis, (h, w),
        jax.device_put(jnp.asarray(lr), spec),
        jax.device_put(jnp.asarray(lc), spec),
        jax.device_put(jnp.asarray(v), spec),
    )
