"""Distance matrices — kernel Gram support.

TPU-native analog of ref: base/distance.hpp:11-339. The reference computes
C = −2·AᵀB then adds column-norm outer sums with hand-written loops (plus
symmetric variants that fill only one triangle); here the whole thing is one
fused XLA expression, and "symmetric" just means Y is X — on TPU there is no
win in computing half a matrix, so the symmetric variants delegate.

Convention: rows are points — ``X`` is (m, d), ``Y`` is (n, d), result is
(m, n). (The reference's ``dir=COLUMNS`` form is this with transposed inputs.)
Like the reference's ``EuclideanDistanceMatrix``, the Euclidean variant
returns **squared** distances.
"""

from __future__ import annotations

import jax.numpy as jnp


def euclidean_distance_matrix(X: jnp.ndarray, Y: jnp.ndarray) -> jnp.ndarray:
    """Squared Euclidean distances D[i,j] = ‖xᵢ − yⱼ‖²
    (ref: base/distance.hpp:11-36 — Gemm(−2·AᵀB) + norm outer sums)."""
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    nx = jnp.sum(X * X, axis=1)
    ny = jnp.sum(Y * Y, axis=1)
    D = nx[:, None] + ny[None, :] - 2.0 * (X @ Y.T)
    return jnp.maximum(D, 0.0)


def symmetric_euclidean_distance_matrix(X: jnp.ndarray) -> jnp.ndarray:
    """Squared Euclidean distances among rows of X
    (ref: base/distance.hpp:73-134 symmetric variant)."""
    return euclidean_distance_matrix(X, X)


def l1_distance_matrix(X: jnp.ndarray, Y: jnp.ndarray) -> jnp.ndarray:
    """L1 distances D[i,j] = ‖xᵢ − yⱼ‖₁ (ref: base/distance.hpp:136-217).

    O(m·n·d) with a broadcast — no inner-product shortcut exists for L1; the
    reference's triple loop maps to one vectorized reduction.
    """
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    return jnp.sum(jnp.abs(X[:, None, :] - Y[None, :, :]), axis=-1)


def symmetric_l1_distance_matrix(X: jnp.ndarray) -> jnp.ndarray:
    """L1 distances among rows of X (ref: base/distance.hpp:219-297)."""
    return l1_distance_matrix(X, X)
