"""Typed registry of every ``SKYLARK_*`` environment variable.

Before this module existed, ~45 scattered ``os.environ`` reads each
re-implemented the repo's env conventions (off-words, typo-degrades-to-
default) and — worse — a newly added variable had to be *remembered*
into :data:`libskylark_tpu.fleet.replica.PROPAGATED_ENV` or process
replicas silently booted with a different engine environment than their
parent (the r13 poisoned-``os.environ``-child class of bug). Declaring
every variable here once, with its parser, default, doc string and
propagate-to-children flag, makes both problems structural:

- the ``env-registry`` lint rule (:mod:`libskylark_tpu.analysis`)
  rejects any raw ``os.environ`` read of a ``SKYLARK_*`` name outside
  this module, and any reference to an undeclared variable;
- :func:`propagated_names` / :func:`snapshot_propagated` mechanically
  feed the replica spawn path, so a declared-propagating variable can
  never again miss process-replica propagation;
- ``script/lint --env-table`` renders the registry as the generated
  reference table in ``docs/env_vars.rst`` — the docs cannot drift
  from the code because they are emitted from it.

Reads are **never cached here**: ``EnvVar.get()`` consults
``os.environ`` on every call, so tests monkeypatching variables keep
working exactly as before. Modules that deliberately latch a value at
import time (``telemetry.metrics.enabled``, ``utility.timer``) keep
their own latch and read through the registry when they do read.

Parse conventions (the repo's, now in one place):

- *flag*: set-and-not-``"0"``/empty is on (``SKYLARK_TELEMETRY``);
- *off-words*: ``0/off/no/false/""`` disable a path-valued variable
  (``SKYLARK_PLAN_CACHE=off``);
- *typo degrades to default*: a malformed int/float never crashes a
  sketch apply — it falls back to the declared default.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

_UNSET = object()

#: Values that disable a path-valued variable when set explicitly.
OFF_WORDS = ("", "0", "off", "no", "false")


def parse_flag(raw: str) -> bool:
    """On unless empty/``"0"`` (the telemetry/profiler convention)."""
    return raw not in ("", "0")


def parse_bool_default_on(raw: str) -> bool:
    """Off only for an explicit off-word (``SKYLARK_USE_PLAN_CACHE``)."""
    return raw.strip().lower() not in OFF_WORDS


def parse_path_or_off(raw: str) -> Optional[str]:
    """A path, or ``None`` when the value is an off-word."""
    return None if raw.strip().lower() in OFF_WORDS else raw


def parse_int(raw: str) -> int:
    return int(raw)


def parse_positive_int(raw: str) -> int:
    n = int(raw)
    if n <= 0:
        raise ValueError(f"expected a positive integer, got {n}")
    return n


def parse_float(raw: str) -> float:
    return float(raw)


def parse_one(raw: str) -> bool:
    """Strict opt-in: only the literal ``"1"`` enables."""
    return raw == "1"


class EnvVar:
    """One declared variable. ``get()`` parses the live environment
    value (typos degrade to the default); ``raw()``/``is_set()`` serve
    the call sites whose semantics the common parsers can't express —
    both still count as going "through the registry" because the
    *declaration* is what the lint rule, the propagation snapshot and
    the doc table key off."""

    __slots__ = ("name", "default", "parser", "doc", "propagate", "kind")

    def __init__(self, name: str, *, default=None,
                 parser: Optional[Callable[[str], object]] = None,
                 doc: str = "", propagate: bool = False,
                 kind: str = "str"):
        self.name = name
        self.default = default
        self.parser = parser
        self.doc = doc
        self.propagate = propagate
        self.kind = kind

    def raw(self) -> Optional[str]:
        """The unparsed environment value (``None`` when unset)."""
        return os.environ.get(self.name)

    def is_set(self) -> bool:
        return self.name in os.environ

    def get(self, default=_UNSET):
        """Parsed value; the declared default (or ``default=``) when
        unset or malformed — a typo degrades, it never raises."""
        fallback = self.default if default is _UNSET else default
        raw = os.environ.get(self.name)
        if raw is None:
            return fallback
        if self.parser is None:
            return raw
        try:
            return self.parser(raw)
        except (ValueError, TypeError):
            return fallback

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"EnvVar({self.name!r}, default={self.default!r}, "
                f"propagate={self.propagate})")


REGISTRY: Dict[str, EnvVar] = {}


def declare(name: str, *, default=None,
            parser: Optional[Callable[[str], object]] = None,
            doc: str = "", propagate: bool = False,
            kind: str = "str") -> EnvVar:
    """Register one variable (module-definition time only). Raises on a
    duplicate declaration — "declared once" is the whole point."""
    if name in REGISTRY:
        raise ValueError(f"environment variable {name!r} declared twice")
    v = REGISTRY[name] = EnvVar(name, default=default, parser=parser,
                                doc=doc, propagate=propagate, kind=kind)
    return v


def lookup(name: str) -> EnvVar:
    """The declared variable, for dynamic access (the lint rule checks
    literal arguments here against the registry)."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a declared SKYLARK environment variable; "
            f"declare it in libskylark_tpu/base/env.py") from None


def propagated_names() -> Tuple[str, ...]:
    """Names a process replica must agree with its parent on — every
    declaration with ``propagate=True``, in declaration order. Feeds
    ``fleet.replica.PROPAGATED_ENV`` mechanically."""
    return tuple(v.name for v in REGISTRY.values() if v.propagate)


def snapshot_propagated() -> Dict[str, Optional[str]]:
    """Raw snapshot of every propagating variable in this process
    (``None`` marks a variable the child must *unset*)."""
    return {name: os.environ.get(name) for name in propagated_names()}


# ---------------------------------------------------------------------------
# declarations — one per SKYLARK_* variable, grouped by subsystem
# ---------------------------------------------------------------------------

# -- telemetry --------------------------------------------------------------

TELEMETRY = declare(
    "SKYLARK_TELEMETRY", default=False, parser=parse_flag, kind="flag",
    propagate=True,
    doc="Enable telemetry recording (any value but empty/``0``). "
        "``SKYLARK_TELEMETRY_DIR`` also enables it implicitly.")

TELEMETRY_DIR = declare(
    "SKYLARK_TELEMETRY_DIR", default=None, kind="path", propagate=True,
    doc="Directory for the JSONL telemetry exporter; setting it both "
        "enables telemetry and auto-installs the exporter at first "
        "import (docs/observability).")

TPU_PROFILE = declare(
    "SKYLARK_TPU_PROFILE", default=False, parser=parse_flag, kind="flag",
    doc="Enable the phase timers (``utility.timer``); latched at first "
        "use, ``timer.set_enabled`` overrides programmatically.")

# -- engine / executable cache ---------------------------------------------

EXEC_CACHE_SIZE = declare(
    "SKYLARK_EXEC_CACHE_SIZE", default=128, parser=parse_positive_int,
    kind="int",
    doc="Capacity of the in-process executable LRU "
        "(``engine.compiled``); read once at engine import.")

ENGINE_DONATE = declare(
    "SKYLARK_ENGINE_DONATE", default=False, parser=parse_one, kind="flag",
    doc="``1`` lets the public solver entry points donate user operands "
        "(invalidates the caller's arrays on every backend; "
        "docs/performance \"donation caveats\").")

EXEC_CACHE_DIR = declare(
    "SKYLARK_EXEC_CACHE_DIR", default=None, parser=parse_path_or_off,
    kind="path", propagate=True,
    doc="jax persistent *compilation* cache directory (HLO-keyed, "
        "tracing still paid). Deprecated as an AOT artifact-store "
        "alias — set ``SKYLARK_AOT_DIR`` for artifacts.")

ENGINE_STATS_DUMP = declare(
    "SKYLARK_ENGINE_STATS_DUMP", default=None, kind="path",
    doc="Write the engine's reset-proof stats rollup to this path at "
        "process exit (the CI jit-leak gate's artifact).")

AOT_DIR = declare(
    "SKYLARK_AOT_DIR", default=None, parser=parse_path_or_off,
    kind="path", propagate=True,
    doc="Persistent AOT executable artifact store "
        "(``engine.aot``); an off-word disables even when the "
        "deprecated ``SKYLARK_EXEC_CACHE_DIR`` alias is present.")

AOT_LOCK_STALE = declare(
    "SKYLARK_AOT_LOCK_STALE", default=600.0, parser=parse_float,
    kind="float",
    doc="Age in seconds past which a peer's AOT file lock is presumed "
        "dead and broken.")

AOT_LOCK_TIMEOUT = declare(
    "SKYLARK_AOT_LOCK_TIMEOUT", default=600.0, parser=parse_float,
    kind="float",
    doc="Seconds a cold process waits on the cross-process AOT compile "
        "lock before compiling anyway (liveness over single-flight).")

# -- serving / fleet --------------------------------------------------------

#: The flush-kernel backends (the authority — ``engine.serve`` imports
#: this as its ``_KERNEL_BACKENDS``, so the env parser and the
#: executor's ``kernel=`` validation can never accept different sets).
SERVE_KERNEL_BACKENDS = ("pallas", "xla")

SERVE_KERNEL = declare(
    "SKYLARK_SERVE_KERNEL", default=None, kind="choice", propagate=True,
    parser=lambda raw: (raw.strip().lower()
                        if raw.strip().lower() in SERVE_KERNEL_BACKENDS
                        else None),
    doc="One-shot flush-kernel override between the executor argument "
        "and the tune plan cache (``pallas`` | ``xla``; anything else "
        "degrades to cache consultation).")

BOOT_T0 = declare(
    "SKYLARK_BOOT_T0", default=None, parser=parse_float, kind="float",
    doc="Parent's ``time.time()`` at replica spawn; the boot probe "
        "reports honest wall-from-spawn time-to-first-result.")

#: The fleet replica backends (``fleet.ReplicaPool`` imports this so
#: the env parser and the pool's ``backend=`` validation agree).
FLEET_BACKENDS = ("thread", "process", "auto")

FLEET_BACKEND = declare(
    "SKYLARK_FLEET_BACKEND", default="thread", kind="choice",
    parser=lambda raw: (raw.strip().lower()
                        if raw.strip().lower() in FLEET_BACKENDS
                        else "thread"),
    doc="Default ``ReplicaPool`` backend when the constructor does not "
        "pin one: ``thread`` | ``process`` | ``auto`` (process on "
        "hosts with >= 4 cores, thread below — the production "
        "many-core default; docs/fleet \"Process replicas\").")

FLEET_SHM = declare(
    "SKYLARK_FLEET_SHM", default=True, parser=parse_bool_default_on,
    kind="flag",
    doc="Shared-memory operand/result transport for process replicas "
        "(default on; ``0`` forces every payload onto the pickle "
        "pipe — docs/fleet \"Shared-memory transport\").")

FLEET_SHM_MIN_BYTES = declare(
    "SKYLARK_FLEET_SHM_MIN_BYTES", default=16 * 1024,
    parser=parse_int, kind="bytes",
    doc="Arrays at or above this size ride the shared-memory ring; "
        "smaller ones (and non-array values) stay on the pickle pipe "
        "where serialization is cheaper than slot bookkeeping.")

FLEET_SHM_SLOTS = declare(
    "SKYLARK_FLEET_SHM_SLOTS", default=8, parser=parse_positive_int,
    kind="int",
    doc="Slots per shared-memory ring direction (parent->child and "
        "child->parent each get this many); an exhausted ring degrades "
        "to the pickle pipe, never blocks.")

FLEET_SHM_SLOT_BYTES = declare(
    "SKYLARK_FLEET_SHM_SLOT_BYTES", default=1 << 20,
    parser=parse_positive_int, kind="bytes",
    doc="Bytes per shared-memory slot; an operand larger than one slot "
        "falls back to the pickle pipe (counted, not an error).")

FLEET_AUTOSCALE_MIN = declare(
    "SKYLARK_FLEET_AUTOSCALE_MIN", default=1, parser=parse_positive_int,
    kind="int",
    doc="Default ``Autoscaler`` floor: the pool never drains below "
        "this many replicas.")

FLEET_AUTOSCALE_MAX = declare(
    "SKYLARK_FLEET_AUTOSCALE_MAX", default=8, parser=parse_positive_int,
    kind="int",
    doc="Default ``Autoscaler`` ceiling: the pool never grows past "
        "this many replicas.")

FLEET_AUTOSCALE_INTERVAL = declare(
    "SKYLARK_FLEET_AUTOSCALE_INTERVAL", default=0.25, parser=parse_float,
    kind="float",
    doc="Seconds between autoscaler control-loop ticks (the cadence "
        "of the queue-depth evaluation).")

FLEET_AUTOSCALE_UP_DEPTH = declare(
    "SKYLARK_FLEET_AUTOSCALE_UP_DEPTH", default=8, parser=parse_int,
    kind="int",
    doc="Mean queued+in-flight requests per replica at or above which "
        "sustained ticks trigger a scale-up (pack boot).")

FLEET_AUTOSCALE_DOWN_DEPTH = declare(
    "SKYLARK_FLEET_AUTOSCALE_DOWN_DEPTH", default=1, parser=parse_int,
    kind="int",
    doc="Mean queued+in-flight requests per replica below which "
        "sustained ticks trigger a scale-down (SIGTERM drain).")

FLEET_AUTOSCALE_COOLDOWN = declare(
    "SKYLARK_FLEET_AUTOSCALE_COOLDOWN", default=5.0, parser=parse_float,
    kind="float",
    doc="Seconds after any scale event before the controller may act "
        "again (hysteresis against flapping).")

FLEET_HEDGE = declare(
    "SKYLARK_FLEET_HEDGE", default=False, parser=parse_flag, kind="flag",
    propagate=False,
    doc="Router-level hedged requests: mirror a straggling in-flight "
        "request to the second ring-preference replica after a "
        "p99-derived delay and take the first result "
        "(docs/fleet \"Hedged requests\").")

FLEET_HEDGE_DELAY_MS = declare(
    "SKYLARK_FLEET_HEDGE_DELAY_MS", default=None, parser=parse_float,
    kind="float",
    doc="Fixed hedge delay in milliseconds; unset derives the delay "
        "from the live p99 request latency (the r10 histograms).")

FLEET_HEDGE_VERIFY = declare(
    "SKYLARK_FLEET_HEDGE_VERIFY", default=False, parser=parse_flag,
    kind="flag",
    doc="Determinism guard: let the hedge loser complete (instead of "
        "cancelling it) and compare both results bitwise, counting "
        "``fleet.hedge_mismatches`` on divergence (chaos battery).")

# -- stateful serve sessions (libskylark_tpu/sessions) ----------------------

SESSION_DIR = declare(
    "SKYLARK_SESSION_DIR", default=None, parser=parse_path_or_off,
    kind="path", propagate=True,
    doc="Durability root of the stateful serve sessions "
        "(``libskylark_tpu.sessions``): per-session append journals, "
        "checkpoints and meta files live here, and a peer replica "
        "resumes a drained/crashed session from it. Unset: a "
        "process-stable directory under the system temp dir (single-"
        "host handoff still works; set it to shared storage for "
        "cross-host resume). Propagated so process replicas journal "
        "to the same root as their parent.")

SESSION_TTL = declare(
    "SKYLARK_SESSION_TTL", default=600.0, parser=parse_float,
    kind="float",
    doc="Default idle TTL in seconds for stateful serve sessions: a "
        "session untouched this long is evicted (journal and "
        "checkpoint removed; later appends/finalize raise "
        "``SessionEvictedError``). Per-session ``ttl_s`` overrides.")

SESSION_FSYNC_EVERY = declare(
    "SKYLARK_SESSION_FSYNC_EVERY", default=8, parser=parse_positive_int,
    kind="int",
    doc="Journal fsync batching: every Nth append also fsyncs the "
        "session journal. Appends always flush to the OS page cache "
        "(process-crash durable); the fsync cadence bounds what a "
        "whole-machine crash can lose. 1 = fsync every append.")

# -- training jobs (libskylark_tpu/train) -----------------------------------

TRAIN_SLICE_ITERS = declare(
    "SKYLARK_TRAIN_SLICE_ITERS", default=8, parser=parse_positive_int,
    kind="int", propagate=True,
    doc="Default solver iterations per training slice — the unit of "
        "preemption and checkpointing of a train job "
        "(``libskylark_tpu.train``): a slice is never interrupted "
        "mid-step, so this bounds both how long a job can occupy an "
        "idle scheduler slot and how much work a crash can lose past "
        "the last checkpoint. Per-job ``slice_iters`` overrides. "
        "Propagated so process replicas slice identically.")

TRAIN_RETRY_BUDGET = declare(
    "SKYLARK_TRAIN_RETRY_BUDGET", default=3, parser=parse_int,
    kind="int", propagate=True,
    doc="How many failed slices a training job absorbs (requeue and "
        "re-run from the journaled state) before the job fails "
        "terminally. Crash-resume via a peer replica does not consume "
        "this budget — it covers in-process slice errors.")

TRAIN_CKPT_EVERY = declare(
    "SKYLARK_TRAIN_CKPT_EVERY", default=4, parser=parse_positive_int,
    kind="int", propagate=True,
    doc="Checkpoint cadence of training jobs: every Nth slice "
        "boundary writes the solver state through the session "
        "checkpoint path, bounding a crashed replica's journal-replay "
        "cost to at most N slices. 1 = checkpoint every slice.")

TRAIN_DEADLINE_S = declare(
    "SKYLARK_TRAIN_DEADLINE_S", default=600.0, parser=parse_float,
    kind="float", propagate=True,
    doc="Default wall-clock deadline in seconds for a training job "
        "(QoS vocabulary: the job-level budget). A job past its "
        "deadline fails with ``TrainBudgetExhaustedError`` at the "
        "next slice boundary, reporting exact iterations completed. "
        "Per-job ``deadline_s`` overrides.")

# -- distributed sketching (libskylark_tpu/dist) ----------------------------

DIST_SHARD_ROWS = declare(
    "SKYLARK_DIST_SHARD_ROWS", default=8192, parser=parse_positive_int,
    kind="int",
    doc="Default rows per shard task when a ``ShardPlan`` does not pin "
        "``shard_rows`` (``libskylark_tpu.dist``): the unit of "
        "re-executable work in distributed sketching "
        "(docs/distributed).")

DIST_RETRIES = declare(
    "SKYLARK_DIST_RETRIES", default=3, parser=parse_int, kind="int",
    doc="Per-shard retry budget of the distributed-sketch coordinator: "
        "how many times a failed shard task is re-executed (with "
        "reassignment to the next ring-preference replica) before it "
        "is abandoned into the degraded-merge accounting.")

DIST_MIN_COVERAGE = declare(
    "SKYLARK_DIST_MIN_COVERAGE", default=1.0, parser=parse_float,
    kind="float",
    doc="Default ``min_coverage`` gate of a distributed sketch merge: "
        "a merged coverage (fraction of declared rows folded in) below "
        "this raises ``SketchCoverageError`` instead of returning a "
        "degraded result. 1.0 = any abandoned shard raises.")

DIST_HEDGE = declare(
    "SKYLARK_DIST_HEDGE", default=False, parser=parse_flag, kind="flag",
    doc="Mirror straggler shard tasks to the next ring-preference "
        "replica after ``SKYLARK_DIST_HEDGE_DELAY_MS`` and take the "
        "first result (the r15 hedging discipline applied to shard "
        "tasks; bit-equal by construction — shard partials are pure "
        "functions of the plan).")

DIST_HEDGE_DELAY_MS = declare(
    "SKYLARK_DIST_HEDGE_DELAY_MS", default=1000.0, parser=parse_float,
    kind="float",
    doc="Straggler threshold for shard-task hedging: an unresolved "
        "shard task older than this is mirrored when "
        "``SKYLARK_DIST_HEDGE`` is on.")

DIST_SERVE_PIPELINE = declare(
    "SKYLARK_DIST_SERVE_PIPELINE", default=0, parser=parse_int,
    kind="int", propagate=True,
    doc="Pipeline depth of a dist-serve job (``submit_dist_sketch`` "
        "and friends): the maximum concurrently outstanding shard "
        "tasks per job. 0 (default) sizes the window automatically to "
        "2x the fleet — deep enough that ingest, shard compute and "
        "incremental merging overlap, while memory stays bounded at "
        "``depth x`` one sketch-sized partial (docs/distributed).")

DIST_SERVE_MERGE_FANIN = declare(
    "SKYLARK_DIST_SERVE_MERGE_FANIN", default=8,
    parser=parse_positive_int, kind="int", propagate=True,
    doc="Merge fan-in of the incremental dist-serve merger: how many "
        "ready pairwise-tree combines are folded per shard-completion "
        "event. A scheduling knob only — the merge tree itself stays "
        "the canonical pairwise reduction, so the merged bits never "
        "depend on this value (docs/distributed).")

DIST_SERVE_MIN_COVERAGE_INTERACTIVE = declare(
    "SKYLARK_DIST_SERVE_MIN_COVERAGE_INTERACTIVE", default=1.0,
    parser=parse_float, kind="float", propagate=True,
    doc="Default ``min_coverage`` of interactive-class dist-serve "
        "requests. Below 1.0 an interactive request may resolve "
        "EARLY with a quantified ``DegradedSketchResult`` once "
        "coverage reaches the gate and every unresolved shard has "
        "already failed at least once — the latency-SLO trade "
        "(docs/distributed, docs/qos). Per-call ``min_coverage=`` "
        "overrides.")

DIST_SERVE_MIN_COVERAGE_STANDARD = declare(
    "SKYLARK_DIST_SERVE_MIN_COVERAGE_STANDARD", default=1.0,
    parser=parse_float, kind="float", propagate=True,
    doc="Default ``min_coverage`` of standard-class dist-serve "
        "requests. Standard (batch) jobs never resolve early: the "
        "storm runs to completion and the gate applies to the final "
        "merge. Per-call ``min_coverage=`` overrides.")

DIST_SERVE_MIN_COVERAGE_BEST_EFFORT = declare(
    "SKYLARK_DIST_SERVE_MIN_COVERAGE_BEST_EFFORT", default=1.0,
    parser=parse_float, kind="float", propagate=True,
    doc="Default ``min_coverage`` of best_effort-class dist-serve "
        "requests (gate applied to the final merge, no early "
        "resolve). Per-call ``min_coverage=`` overrides.")

FAULT_PLAN = declare(
    "SKYLARK_FAULT_PLAN", default=None, kind="json",
    doc="Deterministic fault-injection plan (inline JSON or a path); "
        "activates the chaos sites process-wide "
        "(docs/resilience).")

LOCK_WITNESS = declare(
    "SKYLARK_LOCK_WITNESS", default=False, parser=parse_flag, kind="flag",
    doc="Instrumented-lock mode: locks built by ``base.locks`` record "
        "their runtime acquisition order and the witness fails on "
        "cycles (enabled in the CI chaos battery; docs/analysis).")

# -- tune / plan cache ------------------------------------------------------

PLAN_CACHE = declare(
    "SKYLARK_PLAN_CACHE", default=None, parser=parse_path_or_off,
    kind="path", propagate=True,
    doc="Autotuner plan-cache file. Unset: the repo/benchmarks or "
        "``~/.cache`` default; an off-word disables persistence.")

USE_PLAN_CACHE = declare(
    "SKYLARK_USE_PLAN_CACHE", default=True, parser=parse_bool_default_on,
    kind="flag",
    doc="Consult the plan cache at dispatch time (default on); "
        "``0`` disables all cached-plan consultation.")

COST_CALIB = declare(
    "SKYLARK_COST_CALIB", default=None, parser=parse_path_or_off,
    kind="path",
    doc="Measured calibration source for the analytic cost model "
        "(``tune/cost.py``): a ``benchmarks/ledger.json``-format file "
        "whose ``cost_calib_<rate>`` records (written by ``bench.py`` "
        "modes) override the hand-set roofline rates for the matching "
        "host class, with provenance tracked per rate. ``auto`` "
        "resolves the repo ledger; unset or an off-word keeps the "
        "pure analytic model (docs/performance).")

# -- sparse serve operands (engine/serve.py, docs/serving) ------------------

SPARSE_MIN_DENSITY = declare(
    "SKYLARK_SPARSE_MIN_DENSITY", default=0.25, parser=parse_float,
    kind="float",
    doc="Density (nnz / height·width) at or above which ``submit_"
        "sparse`` auto-densifies the operand onto the dense serve "
        "path instead of the CSR lanes (counted as "
        "``serve.sparse_densified``). At high density the padded "
        "CSR lanes carry more bytes than the dense operand and the "
        "O(nnz) scatter loses to the dense contraction.")

SPARSE_NNZ_FLOOR = declare(
    "SKYLARK_SPARSE_NNZ_FLOOR", default=64, parser=parse_positive_int,
    kind="int",
    doc="Granularity floor of the serve layer's pow2 **nnz class**: "
        "requests below this many nonzeros share one class, so a "
        "flood of tiny sparse requests coalesces into a single "
        "bucket instead of one per exact nnz.")

SPARSE_KERNEL = declare(
    "SKYLARK_SPARSE_KERNEL", default=None, kind="choice", propagate=True,
    parser=lambda raw: (raw.strip().lower()
                        if raw.strip().lower() in SERVE_KERNEL_BACKENDS
                        else None),
    doc="Flush-kernel pin for the sparse serve family only "
        "(``pallas`` | ``xla``); sits between the executor "
        "``kernel=`` argument and ``SKYLARK_SERVE_KERNEL`` in the "
        "sparse buckets' precedence. Anything else degrades to the "
        "general precedence chain.")

# -- panel-free FWHT tier (sketch/pallas_fwht, docs/performance) ------------

FWHT_KERNEL = declare(
    "SKYLARK_FWHT_KERNEL", default=None, kind="choice", propagate=True,
    parser=lambda raw: (raw.strip().lower()
                        if raw.strip().lower() in SERVE_KERNEL_BACKENDS
                        else None),
    doc="Flush-kernel pin for the SRHT/FWHT serve family only "
        "(``pallas`` | ``xla``); sits between the executor "
        "``kernel=`` argument and ``SKYLARK_SERVE_KERNEL`` in the "
        "SRHT buckets' precedence, mirroring "
        "``SKYLARK_SPARSE_KERNEL``. Anything else degrades to the "
        "general precedence chain.")

FWHT_MIN_N = declare(
    "SKYLARK_FWHT_MIN_N", default=4096, parser=parse_positive_int,
    kind="int", propagate=True,
    doc="Minimum transform length n for the in-kernel Pallas FWHT "
        "path (``sketch.pallas_fwht``); shorter transforms decline "
        "to the XLA lowering — below roughly one stream chunk the "
        "butterfly's in-kernel generation overhead beats nothing.")

FWHT_CM_SDIM = declare(
    "SKYLARK_FWHT_CM_SDIM", default=256, parser=parse_positive_int,
    kind="int", propagate=True,
    doc="Default sketch dimension for ``submit_compressed_matmul`` "
        "when the caller passes a contraction length instead of a "
        "transform. Propagated so process replicas estimate with the "
        "same compression (the error bound scales as 1/sqrt(s)).")

# -- multi-tenant QoS (libskylark_tpu/qos, docs/qos) ------------------------

#: The QoS priority classes, most- to least-protected (the authority —
#: ``qos.tenants`` imports this so the env parser, the scheduler's
#: shed ordering and the tenant registry can never disagree).
QOS_CLASSES = ("interactive", "standard", "best_effort")

QOS_ADAPT = declare(
    "SKYLARK_QOS_ADAPT", default=True, parser=parse_bool_default_on,
    kind="flag", propagate=True,
    doc="Freeze switch for the adaptive batching controller "
        "(``libskylark_tpu.qos.controller``): ``0`` freezes every "
        "executor's per-bucket linger/batch targets at their static "
        "config even when the executor was built with "
        "``adaptive=True``. Default on (controllers run where "
        "requested).")

QOS_DEFAULT_CLASS = declare(
    "SKYLARK_QOS_DEFAULT_CLASS", default="standard", kind="choice",
    propagate=True,
    parser=lambda raw: (raw.strip().lower()
                        if raw.strip().lower() in QOS_CLASSES
                        else "standard"),
    doc="Priority class of requests with no ``tenant=`` (and of "
        "tenants the registry does not know): ``interactive`` | "
        "``standard`` | ``best_effort``. Anything else degrades to "
        "``standard``.")

QOS_SHED_INTERACTIVE = declare(
    "SKYLARK_QOS_SHED_INTERACTIVE", default=0.5, parser=parse_float,
    kind="float",
    doc="DEGRADED-shed fraction of ``max_queue`` for the interactive "
        "class: interactive intake sheds only past this exposure — "
        "the LAST class to shed (docs/qos, \"Shed ordering\").")

QOS_SHED_STANDARD = declare(
    "SKYLARK_QOS_SHED_STANDARD", default=0.25, parser=parse_float,
    kind="float",
    doc="DEGRADED-shed fraction of ``max_queue`` for the standard "
        "class (the pre-QoS ``shed_fraction`` behavior — the executor "
        "argument scales all three class fractions together).")

QOS_SHED_BEST_EFFORT = declare(
    "SKYLARK_QOS_SHED_BEST_EFFORT", default=0.1, parser=parse_float,
    kind="float",
    doc="DEGRADED-shed fraction of ``max_queue`` for the best_effort "
        "class — the FIRST class to shed. Best-effort intake "
        "additionally sheds at half the queue bound even when "
        "healthy, so a best-effort storm can never fill the queue "
        "against higher classes.")

QOS_RATE_DEFAULT = declare(
    "SKYLARK_QOS_RATE_DEFAULT", default=None, parser=parse_float,
    kind="float",
    doc="Default per-tenant admission rate (requests/second) for "
        "tenants registered without an explicit ``rate=``. Unset: "
        "registered tenants are unlimited unless they pin a rate.")

QOS_BURST_DEFAULT = declare(
    "SKYLARK_QOS_BURST_DEFAULT", default=None, parser=parse_float,
    kind="float",
    doc="Default token-bucket burst capacity for rate-limited tenants "
        "without an explicit ``burst=``. Unset: 2x the tenant's rate "
        "(one second of headroom above steady state).")

QOS_ADAPT_INTERVAL = declare(
    "SKYLARK_QOS_ADAPT_INTERVAL", default=0.25, parser=parse_float,
    kind="float",
    doc="Seconds between adaptive-controller ticks (the cadence at "
        "which per-bucket linger/batch targets are re-evaluated "
        "against the class SLOs).")

QOS_SLO_INTERACTIVE_MS = declare(
    "SKYLARK_QOS_SLO_INTERACTIVE_MS", default=25.0, parser=parse_float,
    kind="float",
    doc="p99 request-latency SLO (milliseconds) of the interactive "
        "class — the adaptive controller's target for buckets "
        "carrying interactive traffic.")

QOS_SLO_STANDARD_MS = declare(
    "SKYLARK_QOS_SLO_STANDARD_MS", default=250.0, parser=parse_float,
    kind="float",
    doc="p99 request-latency SLO (milliseconds) of the standard "
        "class.")

QOS_SLO_BEST_EFFORT_MS = declare(
    "SKYLARK_QOS_SLO_BEST_EFFORT_MS", default=5000.0,
    parser=parse_float, kind="float",
    doc="p99 request-latency SLO (milliseconds) of the best_effort "
        "class (throughput-oriented: the controller optimizes padding "
        "waste, not latency, while this holds).")

# -- content-addressed result cache (docs/caching) --------------------------

CACHE = declare(
    "SKYLARK_CACHE", default=False, parser=parse_flag, kind="flag",
    propagate=True,
    doc="Content-addressed result cache + single-flight dedupe on the "
        "serve path (docs/caching). Opt-in (``1``): executors "
        "constructed without an explicit ``cache=`` argument consult "
        "this flag. Propagated so process replicas inherit the "
        "fleet's caching decision.")

CACHE_MAX_BYTES = declare(
    "SKYLARK_CACHE_MAX_BYTES", default=256 * 1024 * 1024,
    parser=parse_positive_int, kind="bytes", propagate=True,
    doc="Per-executor byte budget of the digest->result cache; the "
        "per-class quota fractions partition it. 0-or-invalid "
        "degrades to the default.")

CACHE_QUOTA_INTERACTIVE = declare(
    "SKYLARK_CACHE_QUOTA_INTERACTIVE", default=0.5, parser=parse_float,
    kind="float", propagate=True,
    doc="Fraction of ``SKYLARK_CACHE_MAX_BYTES`` reserved for the "
        "interactive class's cached results. Quotas are hard class "
        "partitions: insertion into one class can only evict that "
        "class's own entries, so a best_effort storm can never evict "
        "an interactive working set (docs/caching, \"Tenant "
        "admission\").")

CACHE_QUOTA_STANDARD = declare(
    "SKYLARK_CACHE_QUOTA_STANDARD", default=0.35, parser=parse_float,
    kind="float", propagate=True,
    doc="Fraction of the cache byte budget reserved for the standard "
        "class (see SKYLARK_CACHE_QUOTA_INTERACTIVE).")

CACHE_QUOTA_BEST_EFFORT = declare(
    "SKYLARK_CACHE_QUOTA_BEST_EFFORT", default=0.15,
    parser=parse_float, kind="float", propagate=True,
    doc="Fraction of the cache byte budget reserved for the "
        "best_effort class (see SKYLARK_CACHE_QUOTA_INTERACTIVE).")

CACHE_SINGLE_FLIGHT_TIMEOUT = declare(
    "SKYLARK_CACHE_SINGLE_FLIGHT_TIMEOUT", default=30.0,
    parser=parse_float, kind="float", propagate=True,
    doc="Seconds an in-flight request stays coalescable: identical "
        "requests arriving later than this behind a still-unresolved "
        "leader start their own flight instead of waiting on a "
        "possibly wedged one (docs/caching, \"Single-flight\").")

# -- network serve front door (docs/networking) -----------------------------

NET_HOST = declare(
    "SKYLARK_NET_HOST", default="127.0.0.1", kind="str",
    doc="Bind address of the TCP serve front door "
        "(:class:`libskylark_tpu.net.server.NetServer`). Loopback by "
        "default — exposing the listener beyond the host is a "
        "deliberate deployment decision, not a default.")

NET_PORT = declare(
    "SKYLARK_NET_PORT", default=0, parser=parse_int, kind="int",
    doc="Bind port of the TCP serve front door. ``0`` (the default) "
        "binds an ephemeral port — read ``NetServer.address`` after "
        "construction (tests, smokes).")

NET_MAX_CONNECTIONS = declare(
    "SKYLARK_NET_MAX_CONNECTIONS", default=256,
    parser=parse_positive_int, kind="int",
    doc="Live-connection ceiling on the front door. A connection past "
        "the ceiling is refused with a structured overload error frame "
        "(code 118, docs/networking) rather than a silent reset.")

NET_INFLIGHT_WINDOW = declare(
    "SKYLARK_NET_INFLIGHT_WINDOW", default=32,
    parser=parse_positive_int, kind="int",
    doc="Per-connection inflight-request window. The reader thread "
        "stops reading once this many responses are unflushed, so a "
        "slow reader backpressures through TCP instead of buffering "
        "responses without bound (docs/networking).")

NET_DRAIN_TIMEOUT_S = declare(
    "SKYLARK_NET_DRAIN_TIMEOUT_S", default=10.0, parser=parse_float,
    kind="float",
    doc="Socket-layer drain budget: how long ``NetServer.drain()`` "
        "(and the SIGTERM preemption hook) waits after GOAWAY for "
        "inflight responses to flush before closing connections.")

NET_RETRY_BUDGET = declare(
    "SKYLARK_NET_RETRY_BUDGET", default=3, parser=parse_int,
    kind="int",
    doc="Transport reconnect-resend attempts per request in "
        ":class:`libskylark_tpu.net.client.NetClient`. Safe by "
        "construction — a re-sent frame is byte-identical, so the "
        "server's single-flight table coalesces it onto the original "
        "flight (docs/networking, \"Retry & idempotency\"). 0 "
        "disables transport retry.")

NET_RETRY_BACKOFF_S = declare(
    "SKYLARK_NET_RETRY_BACKOFF_S", default=0.05, parser=parse_float,
    kind="float",
    doc="Base backoff of the client's reconnect retry loop; actual "
        "sleeps are decorrelated-jittered multiples, capped at 2 s.")

# -- sketch kernels ---------------------------------------------------------

PALLAS_MTILE = declare(
    "SKYLARK_PALLAS_MTILE", default=None, parser=parse_int, kind="int",
    doc="Explicit Pallas m-tile (>= 8); a valid value is a user pin "
        "that beats any cached plan (on-chip sweeps).")

MATMUL_PRECISION = declare(
    "SKYLARK_MATMUL_PRECISION", default=None, kind="choice",
    doc="Ambient jax matmul precision installed at package import "
        "(default ``highest``; ``default`` opts out of installation).")

FASTFOOD_PRECISION = declare(
    "SKYLARK_FASTFOOD_PRECISION", default=None, kind="choice",
    doc="Contraction regime inside the fused fastfood kernel "
        "(``f32`` | ``bf16x3`` | ``bf16``); overrides cached plans.")

PALLAS_PIPELINE = declare(
    "SKYLARK_PALLAS_PIPELINE", default=None, kind="choice",
    doc="Tri-state pipelined-kernel override: unset lets a cached plan "
        "decide, ``1`` forces on, anything else forces off.")

HASH_KERNEL = declare(
    "SKYLARK_HASH_KERNEL", default=None, kind="choice",
    doc="CWT/CountSketch flush kernel override: ``pallas``/``mxu``/"
        "``1``, ``pallas_exact``/``exact``, else the XLA scatter.")

PALLAS_VMEM_BUDGET = declare(
    "SKYLARK_PALLAS_VMEM_BUDGET", default=16 * 1024 * 1024,
    parser=parse_int, kind="bytes",
    doc="Per-core VMEM budget the Pallas kernels plan against "
        "(~16 MiB on current generations; no runtime query API).")

PALLAS_SCRATCH_CAP = declare(
    "SKYLARK_PALLAS_SCRATCH_CAP", default=8 * 1024 * 1024,
    parser=parse_int, kind="bytes",
    doc="VMEM cap for caching the generated operator across m-tiles "
        "(must leave room for the double-buffered pipeline tiles).")

AUTO_MATERIALIZE = declare(
    "SKYLARK_AUTO_MATERIALIZE", default=True,
    parser=parse_bool_default_on, kind="flag",
    doc="Automatic materialize-and-reuse dispatch for OperatorCache "
        "transforms (default on; ``0`` disables — "
        "``sketch/params.py``).")

# -- io ---------------------------------------------------------------------

STREAM_PREFETCH = declare(
    "SKYLARK_STREAM_PREFETCH", default=2, parser=parse_int, kind="int",
    doc="Prefetch depth of the double-buffered streaming overlap "
        "(``io.chunked``); 0 disables the overlap.")

WEBHDFS_RETRIES = declare(
    "SKYLARK_WEBHDFS_RETRIES", default=4, parser=parse_int, kind="int",
    doc="Attempt bound of the WebHDFS transport's default retry "
        "policy.")


__all__ = [
    "EnvVar", "OFF_WORDS", "REGISTRY", "declare", "lookup",
    "parse_flag", "parse_bool_default_on", "parse_path_or_off",
    "parse_int", "parse_positive_int", "parse_float", "parse_one",
    "propagated_names", "snapshot_propagated",
]
