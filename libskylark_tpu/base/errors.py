"""Exception hierarchy with stable error codes.

TPU-native analog of the reference's error-code table and exception classes
(ref: base/exception.hpp:297-430). The codes are kept numerically compatible
(100-112) so that tooling written against the reference's `sl_strerror`
contract keeps working against :func:`strerror`.
"""

from __future__ import annotations


class SkylarkError(Exception):
    """Base of all libskylark_tpu errors (ref: base/exception.hpp:310)."""

    code = 100

    def __init__(self, message: str = ""):
        super().__init__(message or self.__doc__)
        self._trace: list[str] = []

    def append_trace(self, entry: str) -> "SkylarkError":
        """Mirror of the reference's trace-append mechanism
        (ref: base/exception.hpp:262-295)."""
        self._trace.append(entry)
        return self

    @property
    def trace(self) -> list[str]:
        return list(self._trace)


class UnsupportedError(SkylarkError):
    """Operation not supported for the given types/shardings."""

    code = 101


class InvalidParametersError(SkylarkError):
    """Invalid parameters passed to an algorithm or transform."""

    code = 102


class AllocationError(SkylarkError):
    """Device/host memory allocation failure."""

    code = 103


class CommunicationError(SkylarkError):
    """Collective/mesh communication failure (MPI-exception analog)."""

    code = 104


class MeshError(SkylarkError):
    """Mesh/sharding incompatibility (elemental-exception analog)."""

    code = 105


class SparseError(SkylarkError):
    """Sparse-matrix error (combblas-exception analog)."""

    code = 106


class RandgenError(SkylarkError):
    """Random-stream error (random123-exception analog)."""

    code = 107


class SketchError(SkylarkError):
    """Sketch-layer error."""

    code = 108


class NLAError(SkylarkError):
    """NLA-layer error (factorization failed, solver diverged...)."""

    code = 109


class MLError(SkylarkError):
    """ML-layer error."""

    code = 110


class IOError_(SkylarkError):
    """Data IO error."""

    code = 111


class NotImplementedYetError(SkylarkError):
    """Declared in the API surface but not yet implemented."""

    code = 112


class SessionEvictedError(SkylarkError):
    """A stateful serve session is gone: TTL-evicted, finalized, or
    never opened (no registry entry and no journal/checkpoint on disk
    to resume from). Terminal for the session id — the client must
    open a new session and re-stream; retrying the append cannot
    succeed (:mod:`libskylark_tpu.sessions`, docs/sessions)."""

    code = 113


class SketchCoverageError(SkylarkError):
    """A distributed sketch merge could not reach the caller's
    ``min_coverage``: one or more row shards exhausted their retry
    budget and were abandoned, so the merged sketch covers only a
    fraction of the declared rows. The error carries the exact
    ``coverage`` achieved and the missing row ranges — the degraded
    result is *reported*, never silently returned
    (:mod:`libskylark_tpu.dist`, docs/distributed)."""

    code = 114

    def __init__(self, message: str = "", *, coverage: float = 0.0,
                 missing=()):
        super().__init__(message)
        self.coverage = float(coverage)
        self.missing = tuple(tuple(r) for r in missing)


class TenantQuotaError(SkylarkError):
    """A serve request exceeded its tenant's admission quota: the
    tenant's token bucket (:mod:`libskylark_tpu.qos`) was empty when
    the request arrived. Retryable after the bucket refills — the
    error carries ``retry_after_s``, the deterministic time until one
    token is available — but never queued: a rate-limited request is
    refused at admission so it cannot occupy queue space ahead of
    in-quota traffic (docs/qos)."""

    code = 115

    def __init__(self, message: str = "", *, tenant: str = "",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.tenant = str(tenant)
        self.retry_after_s = float(retry_after_s)


class TrainBudgetExhaustedError(SkylarkError):
    """A training job ran out of its iteration budget or wall-clock
    deadline before converging. Terminal for the job, but never
    silent: the error carries ``iterations`` (exactly how many solver
    iterations completed across all slices), ``residual`` (the last
    observed convergence signal) and ``slices`` — the caller decides
    whether to resubmit with a larger budget
    (:mod:`libskylark_tpu.train`, docs/training)."""

    code = 116

    def __init__(self, message: str = "", *, iterations: int = 0,
                 residual=None, slices: int = 0):
        super().__init__(message)
        self.iterations = int(iterations)
        self.residual = None if residual is None else float(residual)
        self.slices = int(slices)


class WireProtocolError(CommunicationError):
    """A network frame violated the serve wire protocol: bad magic,
    CRC mismatch, a torn/truncated frame, an unknown verb, or an
    unencodable value (:mod:`libskylark_tpu.net.wire`,
    docs/networking). Never retried blindly — a malformed frame on a
    stream means the stream itself has lost sync, so the connection
    is torn down and the *client* reconnects and re-sends."""

    code = 117


#: The on-wire error code for :class:`libskylark_tpu.engine.serve
#: .ServeOverloadedError`, which deliberately subclasses RuntimeError
#: (backpressure is a transport condition, not a numerical-taxonomy
#: member) and so cannot carry a ``code`` attribute of its own. The
#: wire codec (:mod:`libskylark_tpu.net.wire`) maps it — and its
#: fleet subclass ``NoHealthyReplicaError`` — to this code in both
#: directions; the reconstructed exception carries ``retry_after_s``.
WIRE_OVERLOADED_CODE = 118


_CODE_TABLE = {
    cls.code: cls
    for cls in [
        SkylarkError,
        UnsupportedError,
        InvalidParametersError,
        AllocationError,
        CommunicationError,
        MeshError,
        SparseError,
        RandgenError,
        SketchError,
        NLAError,
        MLError,
        IOError_,
        NotImplementedYetError,
        SessionEvictedError,
        SketchCoverageError,
        TenantQuotaError,
        TrainBudgetExhaustedError,
        WireProtocolError,
    ]
}


def strerror(code: int) -> str:
    """Human-readable message for an error code (ref: base/exception.hpp:256)."""
    cls = _CODE_TABLE.get(code)
    if cls is None:
        return f"unknown error code {code}"
    return cls.__doc__.split("\n")[0]


def from_code(code: int, message: str = "") -> SkylarkError:
    return _CODE_TABLE.get(code, SkylarkError)(message)
