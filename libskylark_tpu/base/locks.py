"""Named locks and the runtime lock-order witness.

Every lock in the threaded serving surface is constructed through
:func:`make_lock` (or :func:`make_rlock`) with a stable dotted **site
name** (``"serve.state"``, ``"engine.cache"``, ...). In normal
operation the factory returns a plain ``threading.Lock`` — zero
wrapping, zero overhead, bit-identical behavior to the direct
constructor it replaced.

With the witness enabled (``SKYLARK_LOCK_WITNESS=1`` or
:func:`enable_witness` before the locks are constructed), the factory
returns instrumented locks that record the **actual runtime
acquisition order**: acquiring ``B`` while holding ``A`` adds the edge
``A → B`` to a process-global graph, and an edge that closes a cycle
is recorded as an ordering violation (the r9 class of bug: two code
paths taking the same pair of locks in opposite orders deadlock only
under the right interleaving — the witness catches the *order*, which
both paths exhibit on every run, instead of the deadlock, which
neither may).

This is the runtime half of the lock-discipline story: the static
``lock-discipline`` rule in :mod:`libskylark_tpu.analysis` derives the
same graph from the AST (keyed on the same site names), and the CI
chaos battery runs one full leg under instrumented locks so the two
graphs are validated against each other (docs/analysis).

Witness failures are **recorded, not raised** at the acquisition site
— raising inside ``acquire`` would turn a diagnosed ordering bug into
an undiagnosable half-locked teardown. Tests and the chaos battery
call :func:`check_witness` (raises :class:`LockOrderError` listing
every violation) at a safe point instead.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from libskylark_tpu.base import env as _env

_FORCED: Optional[bool] = None


def witness_enabled() -> bool:
    """Whether newly constructed locks are instrumented
    (``SKYLARK_LOCK_WITNESS`` or :func:`enable_witness`)."""
    if _FORCED is not None:
        return _FORCED
    return bool(_env.LOCK_WITNESS.get())


def enable_witness(on: bool = True) -> None:
    """Programmatic switch (overrides the environment gate). Only locks
    constructed *after* the switch are instrumented — enable before
    building the executors/pools under test."""
    global _FORCED
    _FORCED = bool(on)


class LockOrderError(RuntimeError):
    """Raised by :func:`check_witness` when the witness recorded at
    least one lock-order violation."""


class _Witness:
    """Process-global acquisition-order recorder. Thread-safe; the
    held-stack is thread-local, the graph is shared."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        # site name -> set of site names acquired while it was held
        self._edges: Dict[str, Set[str]] = {}
        self._violations: List[dict] = []
        self._acquisitions = 0

    # -- per-thread held stack --

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- graph --

    def _reaches(self, src: str, dst: str) -> bool:
        """Whether ``dst`` is reachable from ``src`` in the recorded
        graph (caller holds ``self._lock``)."""
        seen = {src}
        stack = [src]
        while stack:
            for nxt in self._edges.get(stack.pop(), ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    def note_acquire(self, name: str) -> None:
        held = self._held()
        with self._lock:
            self._acquisitions += 1
            for h in held:
                if h == name:
                    continue  # re-entrant RLock hold, not an ordering
                s = self._edges.setdefault(h, set())
                if name in s:
                    continue
                # adding h -> name: a path name ~> h means a cycle —
                # some thread has taken these sites in the other order
                if self._reaches(name, h):
                    self._violations.append({
                        "edge": (h, name),
                        "held": list(held),
                        "thread": threading.current_thread().name,
                    })
                s.add(name)
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- reporting --

    def report(self) -> dict:
        with self._lock:
            return {
                "acquisitions": self._acquisitions,
                "edges": {a: sorted(b) for a, b in
                          sorted(self._edges.items())},
                "violations": [dict(v) for v in self._violations],
            }

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._violations.clear()
            self._acquisitions = 0


_WITNESS = _Witness()


def witness_report() -> dict:
    """The recorded graph: ``{"acquisitions", "edges", "violations"}``
    (edges keyed on lock site names)."""
    return _WITNESS.report()


def reset_witness() -> None:
    """Drop the recorded graph and violations (tests)."""
    _WITNESS.reset()


def check_witness() -> None:
    """Raise :class:`LockOrderError` if any acquisition closed a cycle
    in the recorded lock-order graph."""
    rep = _WITNESS.report()
    if rep["violations"]:
        lines = [
            f"  {a} -> {b} (held {v['held']}, thread {v['thread']})"
            for v in rep["violations"] for a, b in (v["edge"],)
        ]
        raise LockOrderError(
            "lock-order witness recorded %d cycle-closing "
            "acquisition(s):\n%s" % (len(rep["violations"]),
                                     "\n".join(lines)))


class WitnessLock:
    """A ``threading.Lock`` that reports acquire/release to the
    witness. Duck-compatible where the repo needs it: ``with``,
    ``acquire(blocking, timeout)``, ``locked()``, and the
    ``_is_owned`` probe ``threading.Condition`` uses."""

    _inner_factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = name
        self._inner = self._inner_factory()
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            _WITNESS.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._owner = None
        _WITNESS.note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessLock {self.name!r} at {id(self):#x}>"


class WitnessRLock(WitnessLock):
    """Re-entrant variant (no current in-repo user; completeness)."""

    _inner_factory = staticmethod(threading.RLock)

    def __init__(self, name: str):
        super().__init__(name)
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
            _WITNESS.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count == 0:
            self._owner = None
        _WITNESS.note_release(self.name)
        self._inner.release()


def make_lock(name: str):
    """A lock for the named acquisition site: a plain
    ``threading.Lock`` normally, a :class:`WitnessLock` under the
    witness. The name is the site's identity in both the runtime
    witness graph and the static ``lock-discipline`` graph — keep it
    stable and dotted (``"<subsystem>.<role>"``)."""
    if witness_enabled():
        return WitnessLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """Re-entrant counterpart of :func:`make_lock`."""
    if witness_enabled():
        return WitnessRLock(name)
    return threading.RLock()


__all__ = [
    "LockOrderError", "WitnessLock", "WitnessRLock", "check_witness",
    "enable_witness", "make_lock", "make_rlock", "reset_witness",
    "witness_enabled", "witness_report",
]
