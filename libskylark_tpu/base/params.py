"""Base parameter struct for all algorithms.

Analog of ref: base/params.hpp:208-228 — every algorithm's params derives from
this, carrying logging/debug knobs. JSON-loadable like the reference's
ptree-backed params (ref: nla/svd.hpp:43-52), which is how the high-level API
passes params as strings.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import Any, TextIO


@dataclasses.dataclass
class Params:
    am_i_printing: bool = False
    log_level: int = 0
    debug_level: int = 0
    prefix: str = ""
    log_stream: TextIO = dataclasses.field(default=sys.stdout, repr=False)

    def log(self, level: int, message: str) -> None:
        if self.am_i_printing and self.log_level >= level:
            print(f"{self.prefix}{message}", file=self.log_stream)

    def to_dict(self) -> dict[str, Any]:
        d = {}
        for f in dataclasses.fields(self):
            if f.name == "log_stream":
                continue
            d[f.name] = getattr(self, f.name)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    @classmethod
    def from_json(cls, s: str):
        return cls.from_dict(json.loads(s))
