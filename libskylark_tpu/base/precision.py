"""Matmul-precision policy for solver paths.

The reference is float64 end-to-end (SURVEY.md §7 "f64 policy"). On TPU,
float32 matmuls lower to bfloat16 MXU passes by default — harmless for
sketch *application* (random projections are statistically robust to
rounding) but destructive for iterative solvers, cached factorizations, and
power iterations, where rounding compounds across iterations (observed:
Block-ADMM converging on CPU but stalling on TPU with identical inputs).

Policy: solver entry points are wrapped in ``solver_precision()`` which
raises matmul precision to full float32 ("highest" = 6-pass bf16) for
everything traced inside; sketch applies stay at the fast default. Override
globally with ``set_solver_precision`` (e.g. "default" to reclaim MXU speed
when accuracy is known to tolerate it, or for benchmarking)."""

from __future__ import annotations

import contextlib
import functools

import jax

_SOLVER_PRECISION = "highest"


def set_solver_precision(value: str) -> None:
    """Set the global solver matmul precision: "default", "float32"/"highest",
    or "tensorfloat32"."""
    global _SOLVER_PRECISION
    _SOLVER_PRECISION = value


def get_solver_precision() -> str:
    return _SOLVER_PRECISION


@contextlib.contextmanager
def solver_precision():
    """Context raising matmul precision for ops traced within."""
    if _SOLVER_PRECISION == "default":
        yield
    else:
        with jax.default_matmul_precision(_SOLVER_PRECISION):
            yield


def with_solver_precision(fn):
    """Decorator applying :func:`solver_precision` around ``fn`` — used on
    every iterative-solver and factorization entry point."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with solver_precision():
            return fn(*args, **kwargs)

    return wrapped
