"""Matmul-precision policy for solver paths.

The reference is float64 end-to-end (SURVEY.md §7 "f64 policy"). On TPU,
float32 matmuls lower to bfloat16 MXU passes by default — harmless for
sketch *application* (random projections are statistically robust to
rounding) but destructive for iterative solvers, cached factorizations, and
power iterations, where rounding compounds across iterations (observed:
Block-ADMM converging on CPU but stalling on TPU with identical inputs).

Policy: solver entry points are wrapped in ``solver_precision()`` which
raises matmul precision to full float32 ("highest" = 6-pass bf16) for
everything traced inside; sketch applies stay at the fast default. Override
globally with ``set_solver_precision`` (e.g. "default" to reclaim MXU speed
when accuracy is known to tolerate it, or for benchmarking)."""

from __future__ import annotations

import contextlib
import functools

import jax

_SOLVER_PRECISION = "highest"

# What install_default_matmul_precision actually installed (None when the
# user opted out with SKYLARK_MATMUL_PRECISION=default): the baseline for
# telling "ambient is just the library default" apart from "the user
# explicitly pinned a policy" (r4 advisor — throughput paths that opt into
# their own regime must yield to an explicit user policy, context included).
_INSTALLED_AMBIENT: str | None = None


_warned_private_state_moved = False


def ambient_matmul_precision() -> str | None:
    """The effective ambient matmul precision, context-aware: inside a
    user's ``jax.default_matmul_precision(...)`` block this reads the
    context value, not just the global config. When the private
    ``jax._src.config`` State API has moved (a jax upgrade), this
    silently degrades to the GLOBAL config — context pins become
    invisible to the pinned-by-user detection — so the first fallback
    emits a one-time warning instead of hiding the capability loss."""
    global _warned_private_state_moved
    try:
        from jax._src.config import default_matmul_precision

        return default_matmul_precision.value
    except Exception:  # private State API moved — fall back to the global
        if not _warned_private_state_moved:
            _warned_private_state_moved = True
            import warnings

            warnings.warn(
                "jax's private default_matmul_precision state moved in "
                f"this jax ({jax.__version__}): context-scoped "
                "jax.default_matmul_precision(...) pins are no longer "
                "detectable and only the global config is honored — "
                "throughput paths may override a context pin. Pin via "
                "SKYLARK_MATMUL_PRECISION or jax.config.update to be "
                "honored unconditionally.",
                RuntimeWarning, stacklevel=2)
        return jax.config.jax_default_matmul_precision


def ambient_precision_pinned_by_user() -> bool:
    """True when the effective ambient precision differs from what the
    package installed at import — i.e. the user pinned a policy via
    ``jax.default_matmul_precision(...)`` or ``jax.config.update``.
    Throughput paths with their own preferred regime (fut WHT bf16x3)
    check this before overriding the ambient setting.

    Known limit: a pin whose value EQUALS the installed default
    ("highest" unless SKYLARK_MATMUL_PRECISION changed it) is
    indistinguishable from the default and is not detected — jax
    exposes no "explicitly set" bit. Users who need the override
    suppressed at exactly that value should set
    ``SKYLARK_MATMUL_PRECISION`` (always honored)."""
    return ambient_matmul_precision() != _INSTALLED_AMBIENT


def install_default_matmul_precision() -> None:
    """Raise jax's *global* default matmul precision to full float32.

    Called once at package import. Rationale (measured on TPU v5e): with
    jax's factory default, every f32 ``jnp.matmul``/``@`` in the XLA path
    lowers to a single bf16 MXU pass — ~4e-2 absolute error on a 2048-deep
    contraction, 400× outside the framework's 1e-4 determinism oracle
    (ref: tests/unit/test_utils.hpp:48). The reference is float64
    end-to-end; an NLA framework whose applies silently round at 2⁻⁸ is
    wrong, not fast. Opt out (or pick another regime) with
    ``SKYLARK_MATMUL_PRECISION`` ∈ {default, high, highest, ...jax names};
    throughput paths opt into bf16 explicitly via sketch/params.py."""
    from libskylark_tpu.base import env as _env

    global _INSTALLED_AMBIENT
    value = _env.MATMUL_PRECISION.get("highest")
    if value == "default":
        return
    try:
        jax.config.update("jax_default_matmul_precision", value)
        _INSTALLED_AMBIENT = value
    except Exception:
        if _env.MATMUL_PRECISION.is_set():
            # a typo must not silently leave the bf16 factory lowering in
            # place — that is the exact failure this function prevents
            import warnings

            warnings.warn(
                f"SKYLARK_MATMUL_PRECISION={value!r} is not a valid jax "
                "matmul precision; falling back to 'highest'"
            )
            jax.config.update("jax_default_matmul_precision", "highest")
            _INSTALLED_AMBIENT = "highest"


def set_solver_precision(value: str) -> None:
    """Set the global solver matmul precision: "default", "float32"/"highest",
    or "tensorfloat32"."""
    global _SOLVER_PRECISION
    _SOLVER_PRECISION = value


def get_solver_precision() -> str:
    return _SOLVER_PRECISION


@contextlib.contextmanager
def solver_precision():
    """Context raising matmul precision for ops traced within."""
    if _SOLVER_PRECISION == "default":
        yield
    else:
        with jax.default_matmul_precision(_SOLVER_PRECISION):
            yield


def with_solver_precision(fn):
    """Decorator applying :func:`solver_precision` around ``fn`` — used on
    every iterative-solver and factorization entry point."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with solver_precision():
            return fn(*args, **kwargs)

    return wrapped
