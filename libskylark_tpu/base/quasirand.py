"""Quasi-Monte-Carlo sequences (leaped Halton) for quasi-random features.

TPU-native analog of ref: base/quasirand.hpp:8-113. Sequence panels are
generated host-side in float64 numpy at transform-build time (they define the
transform, like the reference's lazily-evaluated coordinates) and shipped to
device once; this keeps full integer precision for the radical inverse without
requiring jax x64 mode.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np


def _primes(n: int) -> np.ndarray:
    primes: list[int] = []
    cand = 2
    while len(primes) < n:
        if all(cand % p for p in primes if p * p <= cand):
            primes.append(cand)
        cand += 1
    return np.asarray(primes, dtype=np.int64)


def radical_inverse(base: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Vectorized radical-inverse (ref: base/quasirand.hpp:9-20).

    The reference computes the inverse of ``idx+1`` ("we start indexes from
    0"); we keep that convention. ``base`` and ``idx`` broadcast.
    """
    base = np.asarray(base, dtype=np.int64)
    res = np.broadcast_to(np.asarray(idx, dtype=np.int64) + 1,
                          np.broadcast_shapes(base.shape, np.shape(idx))).copy()
    basef = base.astype(np.float64)
    r = np.zeros(res.shape, dtype=np.float64)
    m = np.broadcast_to(1.0 / basef, res.shape).copy()
    while (res > 0).any():
        r += m * (res % base)
        res //= base
        m /= basef
    return r


class QMCSequence:
    """Abstract QMC sequence (ref: base/quasirand.hpp:22-32)."""

    sequence_type = "qmc"

    def coordinate(self, idx: int, i: int) -> float:
        raise NotImplementedError

    def panel(self, idx_start: int, idx_stop: int, d: int) -> np.ndarray:
        """Coordinates for idx in [idx_start, idx_stop) x dims [0, d);
        shape (idx_stop-idx_start, d)."""
        raise NotImplementedError

    def to_dict(self) -> dict[str, Any]:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "QMCSequence":
        if d.get("sequence_type") == "leaped halton":
            return LeapedHaltonSequence(int(d["d"]), int(d["leap"]))
        raise ValueError(f"Unknown QMC sequence type {d.get('sequence_type')!r}")


class LeapedHaltonSequence(QMCSequence):
    """Leaped Halton: coordinate(idx, i) = radical_inverse(prime(i), idx*leap)
    (ref: base/quasirand.hpp:34-78). Default leap = prime(d), matching the
    reference's ``boost::math::prime(d)`` default (0-indexed, prime(0)=2)."""

    sequence_type = "leaped halton"

    def __init__(self, d: int, leap: int = -1):
        self.d = int(d)
        ps = _primes(self.d + 1)
        self.leap = int(ps[self.d]) if leap in (-1, None) else int(leap)
        self._bases = ps[: self.d]

    def coordinate(self, idx: int, i: int) -> float:
        return float(radical_inverse(self._bases[i], np.int64(idx) * self.leap))

    def panel(self, idx_start: int, idx_stop: int, d: int) -> np.ndarray:
        assert d <= self.d, "panel dimension exceeds sequence dimension"
        idx = (np.arange(idx_start, idx_stop, dtype=np.int64) * self.leap)[:, None]
        return radical_inverse(self._bases[None, :d], idx)

    def to_dict(self) -> dict[str, Any]:
        return {
            "skylark_object_type": "qmc_sequence",
            "sequence_type": "leaped halton",
            "d": self.d,
            "leap": self.leap,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
