"""Counter-based lazy random streams over jax.random.

TPU-native analog of the reference's ``random_samples_array_t``
(ref: base/randgen.hpp:17-193): a *virtual* array of i.i.d. samples in which
element ``i`` is a pure function of (key, i) — order-independent and
replicable on any device/shard, which is the property that makes sketch
application layout-independent and exactly testable ("sharded apply ==
single-device apply with the same seed", ref: tests/unit/DenseSketchApplyElementalTest.cpp:44-101).

Implementation: the stream is generated in fixed-size chunks. Chunk ``c`` of a
stream with allocation key ``k`` is ``sampler(fold_in(fold_in(k, c>>31), c&M), (CHUNK,))``
— so any contiguous slice can be materialized by generating only its covering
chunks, on whichever device needs it. The chunk size is an internal constant:
changing it changes the stream, so it is part of the format (serialized
streams record it).

Distributions mirror the reference's set (ref: utility/distributions.hpp):
normal, uniform real/int, Cauchy, Rademacher, standard Levy (= 1/Gamma(1/2, 2),
ref: utility/distributions.hpp:17-34), exponential.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

# Elements per generation block. Part of the stream format: changing it
# changes every stream's values.
CHUNK = 4096

_MASK31 = (1 << 31) - 1


def chunk_key(key: jax.Array, cid) -> jax.Array:
    """Key for chunk ``cid`` (host int of any size, or traced int32 < 2^31)."""
    if isinstance(cid, (int, np.integer)):
        hi, lo = int(cid) >> 31, int(cid) & _MASK31
        return jr.fold_in(jr.fold_in(key, hi), lo)
    # Traced chunk ids are restricted to < 2^31 (hi word = 0).
    return jr.fold_in(jr.fold_in(key, 0), cid)


# ---------------------------------------------------------------------------
# Distributions
# ---------------------------------------------------------------------------


class Distribution:
    """A named, serializable sampler: maps (key, shape, dtype) -> samples."""

    name: str = "distribution"

    def sample(self, key: jax.Array, shape, dtype=jnp.float32) -> jax.Array:
        raise NotImplementedError

    def from_bits(self, bits: jax.Array) -> jax.Array:
        """Map uint32 bits -> f32 samples (the dense-block fast path; see
        :func:`dense_block`, which detects support structurally — a
        distribution without an override keeps the legacy sample() block
        definition and this method is never called)."""
        raise NotImplementedError(f"{self.name} has no bit transform")

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)  # type: ignore[call-overload]
        d["distribution"] = self.name
        return d

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Distribution":
        d = dict(d)
        cls = _DIST_REGISTRY[d.pop("distribution")]
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class Normal(Distribution):
    mean: float = 0.0
    std: float = 1.0
    name = "normal"

    def sample(self, key, shape, dtype=jnp.float32):
        return self.mean + self.std * jr.normal(key, shape, dtype)

    def from_bits(self, bits):
        from libskylark_tpu.base import threefry as tf

        return self.mean + self.std * tf.bits_to_normal(bits)


@dataclasses.dataclass(frozen=True)
class Uniform(Distribution):
    low: float = 0.0
    high: float = 1.0
    name = "uniform"

    def sample(self, key, shape, dtype=jnp.float32):
        return jr.uniform(key, shape, dtype, minval=self.low, maxval=self.high)

    def from_bits(self, bits):
        from libskylark_tpu.base import threefry as tf

        return tf.bits_to_uniform(bits, self.low, self.high)


@dataclasses.dataclass(frozen=True)
class UniformInt(Distribution):
    """Uniform integers in [low, high] inclusive (boost convention,
    ref: utility/distributions.hpp:84-100)."""

    low: int = 0
    high: int = 1
    name = "uniform_int"

    def sample(self, key, shape, dtype=jnp.int32):
        return jr.randint(key, shape, self.low, self.high + 1, dtype)


@dataclasses.dataclass(frozen=True)
class Cauchy(Distribution):
    loc: float = 0.0
    scale: float = 1.0
    name = "cauchy"

    def sample(self, key, shape, dtype=jnp.float32):
        return self.loc + self.scale * jr.cauchy(key, shape, dtype)

    def from_bits(self, bits):
        from libskylark_tpu.base import threefry as tf

        return self.loc + self.scale * tf.bits_to_cauchy(bits)


@dataclasses.dataclass(frozen=True)
class Rademacher(Distribution):
    name = "rademacher"

    def sample(self, key, shape, dtype=jnp.float32):
        return jr.rademacher(key, shape).astype(dtype)

    def from_bits(self, bits):
        from libskylark_tpu.base import threefry as tf

        return tf.bits_to_rademacher(bits)


@dataclasses.dataclass(frozen=True)
class StandardLevy(Distribution):
    """Standard Levy: 1/Gamma(1/2, scale=2) == 1/Z^2, Z~N(0,1)
    (ref: utility/distributions.hpp:17-34)."""

    name = "standard_levy"

    def sample(self, key, shape, dtype=jnp.float32):
        z = jr.normal(key, shape, dtype)
        return 1.0 / jnp.maximum(z * z, jnp.finfo(dtype).tiny)


@dataclasses.dataclass(frozen=True)
class Exponential(Distribution):
    rate: float = 1.0
    name = "exponential"

    def sample(self, key, shape, dtype=jnp.float32):
        return jr.exponential(key, shape, dtype) / self.rate


@dataclasses.dataclass(frozen=True)
class Gamma(Distribution):
    shape_param: float = 1.0
    scale: float = 1.0
    name = "gamma"

    def sample(self, key, shape, dtype=jnp.float32):
        return self.scale * jr.gamma(key, self.shape_param, shape, dtype)


_DIST_REGISTRY = {
    cls.name: cls
    for cls in [
        Normal,
        Uniform,
        UniformInt,
        Cauchy,
        Rademacher,
        StandardLevy,
        Exponential,
        Gamma,
    ]
}


# ---------------------------------------------------------------------------
# Virtual streams
# ---------------------------------------------------------------------------


def stream_slice(
    key: jax.Array,
    dist: Distribution,
    start: int,
    stop: int,
    dtype=jnp.float32,
    chunk: int = CHUNK,
) -> jax.Array:
    """Materialize elements [start, stop) of the virtual stream.

    ``start``/``stop`` are host-side ints (shard-local slice bounds are static
    under jit). Equivalent of indexing ``random_samples_array_t``
    (ref: base/randgen.hpp:98-115): the result does not depend on what other
    slices anyone else materializes.
    """
    if stop <= start:
        return jnp.zeros((0,), dtype)
    c0 = start // chunk
    c1 = -(-stop // chunk)
    cids = np.arange(c0, c1, dtype=np.int64)
    hi = (cids >> 31).astype(np.int32)
    lo = (cids & _MASK31).astype(np.int32)
    keys = jax.vmap(lambda h, l: jr.fold_in(jr.fold_in(key, h), l))(hi, lo)
    vals = jax.vmap(lambda k: dist.sample(k, (chunk,), dtype))(keys)
    flat = vals.reshape(-1)
    return flat[start - c0 * chunk : stop - c0 * chunk]


def stream_chunks(
    key: jax.Array,
    dist: Distribution,
    first_cid,
    n_chunks: int,
    dtype=jnp.float32,
    chunk: int = CHUNK,
) -> jax.Array:
    """Materialize ``n_chunks`` whole chunks starting at chunk id ``first_cid``.

    ``first_cid`` may be a traced int32 (for use inside lax loops over
    panels); ``n_chunks`` must be static. Returns shape (n_chunks * chunk,).
    """
    cids = first_cid + jnp.arange(n_chunks, dtype=jnp.int32)
    keys = jax.vmap(lambda c: chunk_key(key, c))(cids)
    vals = jax.vmap(lambda k: dist.sample(k, (chunk,), dtype))(keys)
    return vals.reshape(-1)


def dense_block(
    key: jax.Array,
    dist: Distribution,
    rows: int,
    block_id,
    block_cols: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Column block ``block_id`` of a virtual i.i.d. (rows x n) matrix.

    Any shard can materialize any column panel without generating the rest —
    the TPU-native form of the reference's ``realize_matrix_view`` lazy-panel
    trick (ref: sketch/dense_transform_data.hpp:79-152). ``block_id`` may be
    traced.

    Block format (when the distribution has a bit transform): with
    (k0, k1) = key_data(chunk_key(key, b)), ``half = block_cols // 2`` and
    counter c[r, j] = r·half + j, Threefry-2x32-20 of (c, c + rows·half)
    yields two uint32 lanes; the block is
    ``[from_bits(lane0) | from_bits(lane1)]`` columns. Written in explicit
    integer ops (base/threefry.py) so the Pallas fused-apply kernel
    (sketch/pallas_dense.py) can reproduce the exact bits in-kernel.
    Distributions without a bit transform keep the legacy
    ``dist.sample(chunk_key(key, b), ...)`` definition.
    """
    bkey = chunk_key(key, block_id)
    has_bit_transform = type(dist).from_bits is not Distribution.from_bits
    if not has_bit_transform or block_cols % 2:
        return dist.sample(bkey, (rows, block_cols), dtype)

    from libskylark_tpu.base import threefry as tf

    kd = jr.key_data(bkey).astype(jnp.uint32)
    half = block_cols // 2
    c = (
        jnp.arange(rows, dtype=jnp.uint32)[:, None] * jnp.uint32(half)
        + jnp.arange(half, dtype=jnp.uint32)[None, :]
    )
    b0, b1 = tf.threefry2x32(kd[0], kd[1], c, c + jnp.uint32(rows * half))
    block = jnp.concatenate([dist.from_bits(b0), dist.from_bits(b1)], axis=1)
    return block.astype(dtype)


def dense_panel(
    key: jax.Array,
    dist: Distribution,
    rows: int,
    col_start: int,
    col_stop: int,
    block_cols: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Materialize columns [col_start, col_stop) of the virtual (rows x n)
    matrix defined by :func:`dense_block`. Host-side static bounds."""
    b0 = col_start // block_cols
    b1 = -(-col_stop // block_cols)
    blocks = [
        dense_block(key, dist, rows, b, block_cols, dtype) for b in range(b0, b1)
    ]
    panel = blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=1)
    return panel[:, col_start - b0 * block_cols : col_stop - b0 * block_cols]
