"""Local sparse matrix (CSC) and sparse×dense products.

TPU-native analog of ref: base/sparse_matrix.hpp:23-346 (``sparse_matrix_t``):
a CSC container with zero-copy attach from scipy buffers, duplicate-summing
COO construction (ref: set():136), transpose (ref: Transpose:303) and
read-only column views (ref: view:256).

The device-side representation is COO triplets — on TPU, sparse×dense
products are dataflow ``segment_sum`` contractions over the nonzeros (the
XLA-friendly formulation of the reference's CSC scatter loops,
ref: base/Gemm.hpp:335-519), so the CSC column pointers stay host-side and
the (row, col, value) arrays are what lands in HBM. All nnz-shaped arrays
have static shapes, so products are jittable.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors


class SparseMatrix:
    """Immutable local sparse matrix, CSC on host, COO on device.

    Construction never copies the supplied numpy buffers (the reference's
    external-ownership ``attach`` semantics, ref: base/sparse_matrix.hpp:82);
    device placement happens lazily on first ``coo()``.
    """

    def __init__(
        self,
        colptr: np.ndarray,
        rowind: np.ndarray,
        values: np.ndarray,
        shape: Tuple[int, int],
    ):
        self._colptr = np.asarray(colptr, dtype=np.int64)
        self._rowind = np.asarray(rowind, dtype=np.int32)
        self._values = np.asarray(values)
        self._shape = (int(shape[0]), int(shape[1]))
        if len(self._colptr) != self._shape[1] + 1:
            raise errors.InvalidParametersError(
                f"colptr length {len(self._colptr)} != width+1 "
                f"{self._shape[1] + 1}"
            )
        if len(self._rowind) != len(self._values):
            raise errors.InvalidParametersError("rowind/values length mismatch")
        self._coo_cache: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None

    # -- constructors --

    @classmethod
    def from_scipy(cls, A) -> "SparseMatrix":
        """Attach a ``scipy.sparse`` matrix (converted to CSC if needed;
        zero-copy when already CSC — ref: python sketch.py _ScipyAdapter)."""
        import scipy.sparse as sp

        A = A.tocsc()
        return cls(A.indptr, A.indices, A.data, A.shape)

    @classmethod
    def from_coo(
        cls,
        rows,
        cols,
        values,
        shape: Tuple[int, int],
    ) -> "SparseMatrix":
        """Duplicate-summing COO→CSC build (ref: sparse_matrix.hpp set():136)."""
        import scipy.sparse as sp

        A = sp.coo_matrix(
            (np.asarray(values), (np.asarray(rows), np.asarray(cols))),
            shape=shape,
        ).tocsc()
        A.sum_duplicates()
        return cls(A.indptr, A.indices, A.data, A.shape)

    @classmethod
    def from_csr(
        cls,
        data,
        indices,
        indptr,
        shape: Tuple[int, int],
    ) -> "SparseMatrix":
        """Build from CSR parts (the serve wire format — the inverse of
        :meth:`csr_parts`). Converted to the canonical CSC host layout;
        duplicates are summed (ref: sparse_matrix.hpp set():136)."""
        import scipy.sparse as sp

        A = sp.csr_matrix(
            (np.asarray(data), np.asarray(indices), np.asarray(indptr)),
            shape=shape,
        ).tocsc()
        A.sum_duplicates()
        return cls(A.indptr, A.indices, A.data, A.shape)

    @classmethod
    def from_dense(cls, A, threshold: float = 0.0) -> "SparseMatrix":
        import scipy.sparse as sp

        A = np.asarray(A)
        if threshold > 0.0:
            A = np.where(np.abs(A) > threshold, A, 0.0)
        return cls.from_scipy(sp.csc_matrix(A))

    # -- queries (ref: base/query.hpp Height/Width) --

    @property
    def shape(self) -> Tuple[int, int]:
        return self._shape

    @property
    def height(self) -> int:
        return self._shape[0]

    @property
    def width(self) -> int:
        return self._shape[1]

    @property
    def nnz(self) -> int:
        return len(self._values)

    @property
    def density(self) -> float:
        """nnz / (height·width) — the serve layer's auto-densify signal
        (``SKYLARK_SPARSE_MIN_DENSITY``, docs/serving)."""
        cells = self._shape[0] * self._shape[1]
        return (len(self._values) / cells) if cells else 0.0

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def device_dtype(self):
        """dtype of the device-side values (f64 host buffers land as f32 —
        the TPU-native precision policy; pass an explicit dtype to ``coo``
        to override)."""
        return jnp.float32 if self._values.dtype == np.float64 else jnp.dtype(
            self._values.dtype
        )

    @property
    def indptr(self) -> np.ndarray:
        return self._colptr

    @property
    def indices(self) -> np.ndarray:
        return self._rowind

    @property
    def data(self) -> np.ndarray:
        return self._values

    # -- conversions --

    def coo(self, dtype=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Device COO triplets (rows, cols, vals); cached per resolved dtype.

        ``dtype=None`` always resolves to :meth:`device_dtype` (the f32
        precision-policy default) — a cache left behind by an explicit-dtype
        call is never returned for a default-dtype request."""
        eff = jax.dtypes.canonicalize_dtype(
            np.dtype(dtype) if dtype is not None else self.device_dtype
        )
        if self._coo_cache is None or self._coo_cache[2].dtype != eff:
            counts = np.diff(self._colptr)
            cols = np.repeat(
                np.arange(self.width, dtype=np.int32), counts
            )
            self._coo_cache = (
                jnp.asarray(self._rowind),
                jnp.asarray(cols),
                jnp.asarray(self._values, dtype=eff),
            )
        return self._coo_cache

    def csr_parts(self, dtype=None) -> Tuple[np.ndarray, np.ndarray,
                                             np.ndarray]:
        """Canonical CSR parts ``(data, indices, indptr)`` as host numpy
        arrays — row-major, sorted column indices, duplicates summed —
        the lane layout the sparse serve endpoints pack
        (:mod:`libskylark_tpu.engine.serve`, ``submit_sparse``). The
        row-major nonzero order is load-bearing: the serve scatter
        accumulates in exactly this order, which is what makes the CSR
        flush bit-equal to the dense reference's row-order
        ``segment_sum`` (docs/serving, "Sparse operands on the serve
        path"). ``dtype=None`` resolves to :attr:`device_dtype` (the
        f32 precision-policy default)."""
        eff = np.dtype(dtype) if dtype is not None else np.dtype(
            jax.dtypes.canonicalize_dtype(self.device_dtype))
        A = self.to_scipy().tocsr()
        A.sum_duplicates()
        A.sort_indices()
        return (np.asarray(A.data, dtype=eff),
                np.asarray(A.indices, dtype=np.int32),
                np.asarray(A.indptr, dtype=np.int32))

    def todense(self, dtype=None) -> jax.Array:
        r, c, v = self.coo(dtype)
        return jnp.zeros(self._shape, v.dtype).at[r, c].add(v)

    def to_scipy(self):
        import scipy.sparse as sp

        return sp.csc_matrix(
            (self._values, self._rowind, self._colptr), shape=self._shape
        )

    # -- structural ops --

    def transpose(self) -> "SparseMatrix":
        """(ref: base/sparse_matrix.hpp Transpose:303)"""
        return SparseMatrix.from_scipy(self.to_scipy().T)

    @property
    def T(self) -> "SparseMatrix":
        return self.transpose()

    def column_view(self, j0: int, j1: int) -> "SparseMatrix":
        """Read-only view of columns [j0, j1) (ref: view:256) — shares the
        rowind/values buffers."""
        lo, hi = self._colptr[j0], self._colptr[j1]
        return SparseMatrix(
            self._colptr[j0 : j1 + 1] - lo,
            self._rowind[lo:hi],
            self._values[lo:hi],
            (self.height, j1 - j0),
        )

    def __repr__(self) -> str:
        return (
            f"SparseMatrix({self.height}x{self.width}, nnz={self.nnz}, "
            f"dtype={self.dtype})"
        )


def is_sparse_operand(A) -> bool:
    """True for the framework's sparse matrix kinds (local
    :class:`SparseMatrix` or mesh-distributed ``DistSparseMatrix``) —
    the shared predicate for operand dispatch in the solver layers."""
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix

    return isinstance(A, (SparseMatrix, DistSparseMatrix))


# The sparse products route through the engine's executable cache
# (:mod:`libskylark_tpu.engine.compiled`): eagerly, every spmm call
# re-dispatched a gather + multiply + segment_sum op-by-op — repeated
# sparse products over the same shapes (ADMM sweeps, blocked sketch
# loops, the serve layer's densify A/B) paid per-call op dispatch and
# jax-level retracing instead of one cached executable. The wrappers
# are built lazily (first product) so importing ``base.sparse`` never
# pulls the engine, and keyed on the op name + avals (nnz and operand
# shapes are static per call signature), so the jit-leak gate's
# zero-recompile contract covers them.
_COMPILED_PRODUCTS: dict = {}


def _product_kernel(op: str):
    cf = _COMPILED_PRODUCTS.get(op)
    if cf is None:
        from libskylark_tpu.engine.compiled import compiled as _compiled

        if op == "spmm":
            def kern(r, c, v, B, *, segments: int):
                return jax.ops.segment_sum(v[:, None] * B[c], r,
                                           num_segments=segments)
        else:
            def kern(r, c, v, B, *, segments: int):
                return jax.ops.segment_sum(v[:, None] * B[r], c,
                                           num_segments=segments)
        cf = _compiled(kern, name=f"sparse.{op}",
                       static_argnames=("segments",),
                       key_fn=lambda *a, **k: (op,))
        _COMPILED_PRODUCTS[op] = cf
    return cf


def spmm(A: SparseMatrix, B) -> jax.Array:
    """A @ B with A sparse (h×w), B dense (w×k) → dense (h×k).

    Segment-sum over nonzeros (ref: base/Gemm.hpp:335-519 CSC kernels):
    out[r] += v · B[c] for each (r, c, v) — one cached executable per
    (nnz, operand-shape) class via ``engine.compiled``."""
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if B.shape[0] != A.width:
        raise errors.InvalidParametersError(
            f"spmm: A is {A.shape}, B is {B.shape}"
        )
    r, c, v = A.coo(B.dtype)
    out = _product_kernel("spmm")(r, c, v, B, segments=A.height)
    return out[:, 0] if squeeze else out


def spmm_t(A: SparseMatrix, B) -> jax.Array:
    """Aᵀ @ B with A sparse (h×w), B dense (h×k) → dense (w×k)."""
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if B.shape[0] != A.height:
        raise errors.InvalidParametersError(
            f"spmm_t: A is {A.shape}, B is {B.shape}"
        )
    r, c, v = A.coo(B.dtype)
    out = _product_kernel("spmm_t")(r, c, v, B, segments=A.width)
    return out[:, 0] if squeeze else out


def gemm(A, B, transpose_a: bool = False) -> jax.Array:
    """Unified dense/sparse matmul (ref: base/Gemm.hpp's overload set).

    Sparse operands use the segment-sum kernels; dense×dense is a plain
    jnp matmul (sharded inputs flow through, XLA inserts collectives)."""
    a_sp = isinstance(A, SparseMatrix)
    b_sp = isinstance(B, SparseMatrix)
    if a_sp and b_sp:
        # sparse×sparse stays on host (ref: CombBLAS path — out of TPU scope)
        out = (A.to_scipy().T if transpose_a else A.to_scipy()) @ B.to_scipy()
        return SparseMatrix.from_scipy(out)
    if a_sp:
        return spmm_t(A, B) if transpose_a else spmm(A, B)
    if b_sp:
        A = jnp.asarray(A)
        if transpose_a:
            A = A.T
        # A @ B = (Bᵀ @ Aᵀ)ᵀ
        return spmm_t(B, A.T).T
    A = jnp.asarray(A)
    return (A.T if transpose_a else A) @ jnp.asarray(B)
