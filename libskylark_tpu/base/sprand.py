"""Sparse random matrices and hash maps.

TPU-native analog of ref: python-skylark/skylark/sprand.py:9-80 — sparse
i.i.d. samples and the sparse matrix form of a random hash map h:[n]→[t]
(the explicit-matrix view of the CountSketch family). Draws come from
Context counter streams, so matrices are deterministic given (seed,
counter) like everything else in the framework.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.sparse import SparseMatrix


def sample(
    m: int,
    n: int,
    density: float,
    nz_values: Sequence[float],
    nz_prob_dist: Sequence[float],
    context: Context,
) -> SparseMatrix:
    """(m, n) sparse matrix of the given density whose nonzeros are drawn
    i.i.d. from ``nz_values`` with probabilities ``nz_prob_dist``
    (ref: sprand.py sample:9-34)."""
    if not 0.0 <= density <= 1.0:
        raise errors.InvalidParametersError(f"bad density {density}")
    nnz = int(round(density * m * n))
    # positions: draw from the stream until nnz DISTINCT flat indices are
    # collected (scipy.sparse.rand semantics: exact nnz), consuming the
    # uniform-int stream in growing slices
    key = context.allocate().key
    chosen = np.zeros(0, dtype=np.int64)
    lo = 0
    draw = max(2 * nnz, 16)
    while len(chosen) < nnz and lo < 64 * max(nnz, 1):
        batch = np.asarray(randgen.stream_slice(
            key, randgen.UniformInt(0, m * n - 1), lo, lo + draw,
            dtype=jnp.int32), dtype=np.int64)
        lo += draw
        # vectorized first-occurrence dedup, preserving draw order (no
        # positional bias from np.unique's sorting)
        u, first = np.unique(batch, return_index=True)
        u = u[np.argsort(first)]
        u = u[~np.isin(u, chosen, assume_unique=True)]
        chosen = np.concatenate([chosen, u])
    if len(chosen) < nnz:
        raise errors.SkylarkError(
            f"drew {lo} candidates but found only {len(chosen)} distinct "
            f"positions (< nnz={nnz}); density {density} too high for "
            f"rejection sampling"
        )
    flat = chosen[:nnz]
    rows, cols = flat // n, flat % n
    u = np.asarray(randgen.stream_slice(
        context.allocate().key, randgen.Uniform(), 0, max(len(flat), 1),
        dtype=jnp.float32), dtype=np.float64)[: len(flat)]
    cdf = np.cumsum(np.asarray(nz_prob_dist, dtype=np.float64))
    cdf = cdf / cdf[-1]
    vals = np.asarray(nz_values, dtype=np.float64)[
        np.searchsorted(cdf, u, side="right").clip(0, len(nz_values) - 1)]
    return SparseMatrix.from_coo(rows, cols, vals.astype(np.float32), (m, n))


def hashmap(
    t: int,
    n: int,
    context: Context,
    values: str = "rademacher",
    dimension: int = 0,
) -> SparseMatrix:
    """Sparse matrix of a random hash h:[n]→[t]: S[h(i), i] = v(i)
    (dimension=0, t×n) or S[i, h(i)] = v(i) (dimension=1, n×t)
    (ref: sprand.py hashmap:37-80). ``values`` is 'rademacher' (±1,
    CountSketch) or 'ones'."""
    h = np.asarray(randgen.stream_slice(
        context.allocate().key, randgen.UniformInt(0, t - 1), 0, n,
        dtype=jnp.int32), dtype=np.int64)
    if values == "rademacher":
        v = np.asarray(randgen.stream_slice(
            context.allocate().key, randgen.Rademacher(), 0, n,
            dtype=jnp.float32))
    elif values == "ones":
        v = np.ones(n, dtype=np.float32)
    else:
        raise errors.InvalidParametersError(
            f"values must be 'rademacher' or 'ones', got {values!r}")
    i = np.arange(n, dtype=np.int64)
    if dimension == 0:
        return SparseMatrix.from_coo(h, i, v, (t, n))
    return SparseMatrix.from_coo(i, h, v, (n, t))
