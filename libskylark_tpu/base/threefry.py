"""Threefry-2x32-20 counter PRNG, written in plain jnp integer ops.

This is the bit-level definition of the framework's *dense block* stream
format (ref: base/randgen.hpp Random123 Threefry usage:98-115). It exists as
explicit ops — rather than calling ``jax.random`` — so the exact same
sequence of 32-bit adds/xors/rotations can run in three places with
identical bits:

1. the XLA path (:func:`randgen.dense_block`),
2. the Pallas TPU kernel that generates sketch panels inside a fused
   matmul (sketch/pallas_dense.py),
3. any host-side replay (integer ops are bitwise identical on every
   backend).

The algorithm is the public Threefry-2x32 with 20 rounds (5 groups of 4)
from Salmon et al., "Parallel random numbers: as easy as 1, 2, 3" (SC'11) —
the same cipher the reference's Random123 dependency implements.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

# rotation schedule for Threefry-2x32 (Salmon et al. Table 2)
_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
_PARITY = 0x1BD11BDA

# NOTE: every numeric constant below is a weak-typed Python scalar on
# purpose — jnp.uint32(...)/jnp.float32(...) create array constants, which
# a Pallas kernel cannot capture. Weak scalars promote to the operand's
# dtype and trace cleanly both in XLA and inside kernels.


def _rotl(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x << r) | (x >> (32 - r))


def threefry2x32(k0, k1, c0: jnp.ndarray, c1: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Encrypt counter words (c0, c1) under key (k0, k1).

    ``c0``/``c1`` are uint32 arrays; ``k0``/``k1`` are uint32 scalars
    (python ints, numpy scalars, or traced values — e.g. SMEM reads inside
    a Pallas kernel). Returns two uint32 arrays of c0's shape — 64 random
    bits per counter.
    """
    ks2 = k0 ^ k1 ^ _PARITY
    x0 = c0.astype(jnp.uint32) + k0
    x1 = c1.astype(jnp.uint32) + k1
    keys = (k0, k1, ks2)
    for group in range(5):
        r0, r1, r2, r3 = _ROTATIONS[:4] if group % 2 == 0 else _ROTATIONS[4:]
        for r in (r0, r1, r2, r3):
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        # key injection after each 4-round group
        x0 = x0 + keys[(group + 1) % 3]
        x1 = x1 + keys[(group + 2) % 3] + (group + 1)
    return x0, x1


def bits_to_unit(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 bits → f32 uniform in [0, 1) with 24-bit resolution.

    The top 24 bits are bitcast to int32 before the float cast — the value
    fits, and Mosaic (Pallas TPU) has no uint32→f32 cast."""
    import jax

    top = jax.lax.bitcast_convert_type(bits >> 8, jnp.int32)
    return top.astype(jnp.float32) * (2.0**-24)


def bits_to_normal(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 bits → f32 standard normal via inverse-CDF.

    z = √2·erfinv(2u−1) with u clamped away from {0,1}. The integer→(−1,1)
    mapping is bit-exact everywhere; erfinv itself is backend-dependent at
    the ~1e-5 level (the framework's accepted cross-backend drift — the
    reference's oracle tolerance is 1e-4)."""
    import jax

    u = bits_to_unit(bits)
    v = jnp.clip(2.0 * u - 1.0, -1.0 + 2.0**-23, 1.0 - 2.0**-23)
    return 1.4142135623730951 * jax.lax.erf_inv(v)


def bits_to_cauchy(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 bits → f32 standard Cauchy: tan(π(u−1/2)), u clamped."""
    u = bits_to_unit(bits)
    v = jnp.clip(u, 2.0**-24, 1.0 - 2.0**-24)
    return jnp.tan(3.141592653589793 * (v - 0.5))


def bits_to_rademacher(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 bits → ±1 from the top bit."""
    return jnp.where((bits >> 31) == 0, 1.0, -1.0).astype(jnp.float32)


def bits_to_uniform(bits: jnp.ndarray, low: float, high: float) -> jnp.ndarray:
    return low + bits_to_unit(bits) * (high - low)
