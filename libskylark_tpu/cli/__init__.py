"""Command-line drivers mirroring the reference executables.

TPU-native analogs of the compiled CLIs (ref: nla/skylark_svd.cpp,
nla/skylark_linear.cpp, ml/skylark_ml.cpp, ml/skylark_graph_se.cpp,
ml/skylark_community.cpp, ml/skylark_convert2hdf5.cpp). Run as
``python -m libskylark_tpu.cli.skylark_svd [...]`` etc.; each module
exposes ``main(argv) -> int`` for programmatic use and testing.

Flag names and defaults track the reference's boost::program_options
tables so command lines port over mechanically.
"""

from __future__ import annotations

import numpy as np

# fileformat enum (ref: ml/options.hpp:46-52)
LIBSVM_DENSE, LIBSVM_SPARSE, HDF5_DENSE, HDF5_SPARSE = 0, 1, 2, 3


def read_dataset(path: str, fileformat: int, min_d: int = 0):
    """ml/io.hpp:871-890 ``read()`` dispatch equivalent."""
    import libskylark_tpu.io as skio

    if fileformat == LIBSVM_DENSE:
        return skio.read_libsvm(path, min_d=min_d)
    if fileformat == LIBSVM_SPARSE:
        return skio.read_libsvm(path, min_d=min_d, sparse=True)
    if fileformat == HDF5_DENSE:
        return skio.read_hdf5(path, min_d=min_d)
    if fileformat == HDF5_SPARSE:
        return skio.read_hdf5(path, min_d=min_d, sparse=True)
    raise SystemExit(f"unknown fileformat {fileformat}")


def honor_platform_env() -> None:
    """Make an explicit ``JAX_PLATFORMS`` effective for a CLI run even
    where a ``sitecustomize`` pre-imported jax with another platform
    pinned (the env var is only read at first jax import, so
    ``JAX_PLATFORMS=cpu skylark_ml ...`` would otherwise silently target
    — and on a wedged tunnel, hang on — the pinned accelerator).

    Called at the top of every CLI ``main``. Application-level on
    purpose: the library must not mutate platform config at import (a
    script's own ``jax.config.update`` would be clobbered — the ambient
    image exports the pinned platform's env var globally, so "the user
    set it" is undetectable there). Acts only while jax's backends are
    still uninitialized: inside a host process that already chose a
    platform (e.g. the test suite's conftest), it is a no-op."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        # private-API probe in its own guard: if a jax upgrade moves it,
        # "backends state unknown" must still proceed to the update —
        # skipping it would silently disable the exact protection this
        # function exists for
        from jax._src import xla_bridge as _xb

        if getattr(_xb, "_backends", None):
            return  # backends live — too late, and someone chose already
    except Exception:
        pass
    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass  # never block a CLI over a platform hint


def write_ascii_matrix(path: str, M, digits: int = 8) -> None:
    """El::Write(..., El::ASCII) equivalent (ref: nla/skylark_svd.cpp:110)."""
    np.savetxt(path, np.asarray(M), fmt=f"%.{digits}g")


def add_streaming_args(p) -> None:
    """Shared --streaming/--batch-rows flags (bounded-memory sharded
    ingestion; the HDFS-reader analog) for the libsvm-reading CLIs."""
    p.add_argument("--streaming", action="store_true",
                   help="stream the (dense libsvm) file into sharded "
                   "device memory in bounded host memory")
    p.add_argument("--batch-rows", type=int, default=65536,
                   help="rows per streamed batch with --streaming")


def read_streaming(path: str, batch_rows: int):
    """Stream ``path`` into a row-sharded device array over the default
    1D mesh (see io.read_libsvm_sharded)."""
    import libskylark_tpu.io as skio
    from libskylark_tpu.parallel import make_mesh

    return skio.read_libsvm_sharded(path, make_mesh(),
                                    batch_rows=batch_rows)
