"""skylark_community: seeded local community detection.

TPU-native analog of ref: ml/skylark_community.cpp:104-300 — loads an
arc-list graph, then finds a low-conductance cluster around seed
vertices via time-dependent PPR + sweep cut; interactive mode reads
seeds from stdin, batch mode takes them on the command line.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_community",
        description="Seeded community detection "
        "(ref: ml/skylark_community.cpp)",
    )
    p.add_argument("graphfile", help="arc-list graph file")
    p.add_argument("seeds", nargs="*", help="seed vertices (batch mode)")
    p.add_argument("-i", "--interactive", action="store_true",
                   help="read seed vertices from stdin, one line per query")
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("-r", "--recursive", action="store_true",
                   help="recursively expand the cluster as new seeds")
    p.add_argument("-c", "--cond", action="store_true",
                   help="in quiet mode prefix output with conductance")
    p.add_argument("--gamma", type=float, default=5.0)
    p.add_argument("--alpha", type=float, default=0.85)
    p.add_argument("--epsilon", type=float, default=0.001)
    p.add_argument("-n", "--numeric", action="store_true",
                   help="vertex names are numeric ids")
    return p


def _run_query(G, seeds, args):
    from libskylark_tpu.ml.graph import find_local_cluster

    t0 = time.time()
    cluster, cond = find_local_cluster(
        G, seeds, alpha=args.alpha, gamma=args.gamma,
        epsilon=args.epsilon, recursive=args.recursive,
    )
    elapsed = time.time() - t0
    members = " ".join(str(v) for v in sorted(cluster, key=str))
    if args.quiet:
        print(f"{cond:.3f} {members}" if args.cond else members)
    else:
        print(f"Conductance = {cond:.3f} (took {elapsed:.2e} sec)")
        print(f"Cluster: {members}")


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    from libskylark_tpu.ml.graph import Graph

    t0 = time.time()
    G = Graph()
    with open(args.graphfile) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            toks = line.split()
            u, v = toks[0], toks[1]
            if args.numeric:
                u, v = int(u), int(v)
            G.add_edge(u, v)  # Graph.add_edge inserts both directions
    if not args.quiet:
        print(f"Reading the graph... took {time.time() - t0:.2e} sec")

    def parse_seed(tok):
        return int(tok) if args.numeric else tok

    if args.interactive:
        for line in sys.stdin:
            toks = line.split()
            if not toks:
                continue
            seeds = [parse_seed(t) for t in toks]
            missing = [s for s in seeds if not G.has_vertex(s)]
            if missing:
                print(f"seed(s) not in graph: {missing}", file=sys.stderr)
                continue
            _run_query(G, seeds, args)
        return 0

    if not args.seeds:
        print("error: no seeds given (use --interactive or list seeds)",
              file=sys.stderr)
        return 2
    seeds = [parse_seed(t) for t in args.seeds]
    missing = [s for s in seeds if not G.has_vertex(s)]
    if missing:
        print(f"error: seed(s) not in graph: {missing}", file=sys.stderr)
        return 2
    _run_query(G, seeds, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
