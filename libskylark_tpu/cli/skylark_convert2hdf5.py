"""skylark_convert2hdf5: libsvm → HDF5 dataset conversion.

TPU-native analog of ref: ml/skylark_convert2hdf5.cpp:30-60 — mode 0
converts to the dense layout ("X"/"Y" datasets), mode 1 to the sparse
layout ("dimensions"/"indptr"/"indices"/"values"/"Y").
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_convert2hdf5",
        description="libsvm → HDF5 converter "
        "(ref: ml/skylark_convert2hdf5.cpp)",
    )
    p.add_argument("inputfile", help="libsvm input file")
    p.add_argument("hdf5file", help="HDF5 output file")
    p.add_argument("--mode", type=int, default=0, choices=[0, 1],
                   help="0: dense layout, 1: sparse layout")
    p.add_argument("--min-d", type=int, default=0)
    return p


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    import libskylark_tpu.io as skio

    X, Y = skio.read_libsvm(args.inputfile, sparse=args.mode == 1,
                            min_d=args.min_d)
    skio.write_hdf5(args.hdf5file, X, Y)
    print(f"input: {args.inputfile} hdf5file: {args.hdf5file} "
          f"mode: {args.mode} min_d: {args.min_d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
