"""skylark_graph_se: approximate adjacency spectral embedding of a graph.

TPU-native analog of ref: ml/skylark_graph_se.cpp — reads an arc-list
graph, runs ApproximateASE, writes prefix.V.txt (embedding vectors) and
prefix.index.txt (vertex order).
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_graph_se",
        description="Approximate adjacency spectral embedding "
        "(ref: ml/skylark_graph_se.cpp)",
    )
    p.add_argument("graphfile", help="arc-list graph file")
    p.add_argument("-s", "--seed", type=int, default=38734)
    p.add_argument("-k", "--rank", type=int, default=6)
    p.add_argument("-i", "--powerits", type=int, default=2)
    p.add_argument("--skipqr", action="store_true")
    p.add_argument("-r", "--ratio", type=int, default=2)
    p.add_argument("-a", "--additive", type=int, default=0)
    p.add_argument("-n", "--numeric", action="store_true",
                   help="vertex names are numeric ids")
    p.add_argument("--prefix", default="out")
    return p


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.cli import write_ascii_matrix
    from libskylark_tpu.ml.graph import Graph, approximate_ase
    from libskylark_tpu.nla.svd import ApproximateSVDParams

    t0 = time.time()
    G = Graph()
    with open(args.graphfile) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            toks = line.split()
            u, v = toks[0], toks[1]
            if args.numeric:
                u, v = int(u), int(v)
            G.add_edge(u, v)
    print(f"Reading the graph... took {time.time() - t0:.2e} sec")

    params = ApproximateSVDParams(
        num_iterations=args.powerits,
        oversampling_ratio=args.ratio,
        oversampling_additive=args.additive,
        skip_qr=args.skipqr,
    )
    t0 = time.time()
    X, indexmap = approximate_ase(G, args.rank, Context(seed=args.seed),
                                  params)
    print(f"Computing embeddings... took {time.time() - t0:.2e} sec")

    write_ascii_matrix(args.prefix + ".V.txt", X)
    with open(args.prefix + ".index.txt", "w") as f:
        for v in indexmap:
            f.write(f"{v}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
