"""skylark_linear: sketch-accelerated least-squares solve from file.

TPU-native analog of ref: nla/skylark_linear.cpp:97-201 — reads a libsvm
regression problem, solves min ‖Ax − b‖₂ with FastLeastSquares (Blendenpik)
or sketch-and-solve, writes the solution vector.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_linear",
        description="Sketched least squares (ref: nla/skylark_linear.cpp)",
    )
    p.add_argument("inputfile", help="input file (libsvm format)")
    p.add_argument("-d", "--directory", action="store_true")
    p.add_argument("-s", "--seed", type=int, default=38734)
    p.add_argument("-p", "--highprecision", action="store_true",
                   help="accurate sketch-preconditioned solve (Blendenpik); "
                   "default is sketch-and-solve")
    p.add_argument("-f", "--single", action="store_true",
                   help="kept for command-line parity (f32 is the default)")
    p.add_argument("--prefix", default="out",
                   help="solution written to prefix.x.txt")
    from libskylark_tpu.cli import add_streaming_args

    add_streaming_args(p)
    return p


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    import jax.numpy as jnp

    import libskylark_tpu.io as skio
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.cli import write_ascii_matrix
    from libskylark_tpu.nla.least_squares import (
        approximate_least_squares,
        fast_least_squares,
    )

    t0 = time.time()
    if args.streaming:
        if args.directory:
            print("error: --streaming reads a single libsvm file",
                  file=sys.stderr)
            return 2
        from libskylark_tpu.cli import read_streaming

        X, Y = read_streaming(args.inputfile, args.batch_rows)
    else:
        reader = skio.read_dir_libsvm if args.directory else skio.read_libsvm
        X, Y = reader(args.inputfile)
    print(f"Reading the matrix... took {time.time() - t0:.2e} sec")

    context = Context(seed=args.seed)
    t0 = time.time()
    if args.highprecision:
        x = fast_least_squares(jnp.asarray(X), jnp.asarray(Y), context)
        if isinstance(x, tuple):
            x = x[0]
    else:
        x = approximate_least_squares(jnp.asarray(X), jnp.asarray(Y), context)
    print(f"Solving the least squares... took {time.time() - t0:.2e} sec")

    write_ascii_matrix(args.prefix + ".x.txt", x)
    return 0


if __name__ == "__main__":
    sys.exit(main())
