"""skylark_ml: kernel-machine training/prediction via block-ADMM.

TPU-native analog of ref: ml/skylark_ml.cpp:15-172 + ml/options.hpp —
train mode builds a BlockADMMSolver from (loss, regularizer, kernel)
options and saves a HilbertModel; test mode loads a model and reports
accuracy/error; flags mirror the reference's boost::program_options
table (ml/options.hpp:116-197) including the integer enums.
"""

from __future__ import annotations

import argparse
import sys
import time

# enums (ref: ml/options.hpp:26-52)
LOSSES = ["SQUARED", "LAD", "HINGE", "LOGISTIC"]
REGULARIZERS = ["NOREG", "L2", "L1"]
KERNELS = ["LINEAR", "GAUSSIAN", "POLYNOMIAL", "LAPLACIAN",
           "EXPSEMIGROUP", "MATERN"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_ml",
        description="Block-ADMM kernel machines (ref: ml/skylark_ml.cpp)",
    )
    p.add_argument("trainfile", nargs="?", default="")
    p.add_argument("modelfile_pos", nargs="?", default="")
    p.add_argument("-l", "--lossfunction", type=int, default=0,
                   help="0:SQUARED 1:LAD 2:HINGE 3:LOGISTIC")
    p.add_argument("-r", "--regularizer", type=int, default=0,
                   help="0:None 1:L2 2:L1")
    p.add_argument("-k", "--kernel", type=int, default=0,
                   help="0:LINEAR 1:GAUSSIAN 2:POLYNOMIAL 3:LAPLACIAN "
                   "4:EXPSEMIGROUP 5:MATERN")
    p.add_argument("-g", "--kernelparam", type=float, default=1.0)
    p.add_argument("-x", "--kernelparam2", type=float, default=0.0)
    p.add_argument("-y", "--kernelparam3", type=float, default=1.0)
    p.add_argument("-c", "--lambda", dest="lam", type=float, default=0.0)
    p.add_argument("-e", "--tolerance", type=float, default=0.001)
    p.add_argument("--rho", type=float, default=1.0)
    p.add_argument("-s", "--seed", type=int, default=12345)
    p.add_argument("-f", "--randomfeatures", type=int, default=0,
                   help="0 => exact linear features")
    p.add_argument("-n", "--numfeaturepartitions", type=int, default=1)
    p.add_argument("--regression", action="store_true")
    p.add_argument("--usefast", action="store_true")
    p.add_argument("-q", "--usequasi", type=int, default=0,
                   help="0: Monte Carlo, 1: leaped Halton (quasi)")
    p.add_argument("--cachetransforms", action="store_true")
    p.add_argument("--decisionvals", action="store_true")
    p.add_argument("--fileformat", type=int, default=0,
                   help="0 libsvm-dense, 1 libsvm-sparse, 2 hdf5-dense, "
                   "3 hdf5-sparse")
    p.add_argument("-i", "--MAXITER", type=int, default=10)
    from libskylark_tpu.cli import add_streaming_args

    add_streaming_args(p)
    p.add_argument("--modelfile", default="")
    p.add_argument("--valfile", default="")
    p.add_argument("--testfile", default="")
    p.add_argument("--outputfile", default="")
    p.add_argument("--checkpoint-dir", default="",
                   help="persist ADMM state here every "
                        "--checkpoint-every iterations; rerunning with "
                        "the same directory resumes (bit-identical to "
                        "an uninterrupted run)")
    p.add_argument("--checkpoint-every", type=int, default=10)
    return p


def _make_kernel(args, d: int):
    from libskylark_tpu.ml import kernels as K

    kp, kp2, kp3 = args.kernelparam, args.kernelparam2, args.kernelparam3
    kind = KERNELS[args.kernel]
    if kind == "LINEAR":
        return K.Linear(d)
    if kind == "GAUSSIAN":
        return K.Gaussian(d, sigma=kp)
    if kind == "POLYNOMIAL":
        return K.Polynomial(d, q=int(kp), c=kp2, gamma=kp3)
    if kind == "LAPLACIAN":
        return K.Laplacian(d, sigma=kp)
    if kind == "EXPSEMIGROUP":
        return K.ExpSemigroup(d, beta=kp)
    if kind == "MATERN":
        return K.Matern(d, nu=kp, l=kp2 or 1.0)
    raise SystemExit(f"unknown kernel {args.kernel}")


def _make_loss(args):
    from libskylark_tpu.algorithms import prox

    return {
        "SQUARED": prox.SquaredLoss,
        "LAD": prox.LADLoss,
        "HINGE": prox.HingeLoss,
        "LOGISTIC": prox.LogisticLoss,
    }[LOSSES[args.lossfunction]]()


def _make_regularizer(args):
    from libskylark_tpu.algorithms import prox

    return {
        "NOREG": prox.EmptyRegularizer,
        "L2": prox.L2Regularizer,
        "L1": prox.L1Regularizer,
    }[REGULARIZERS[args.regularizer]]()


def _train(args) -> int:
    import numpy as np

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.cli import read_dataset
    from libskylark_tpu.ml.admm import BlockADMMSolver

    modelfile = args.modelfile or args.modelfile_pos
    if not modelfile:
        print("error: modelfile required", file=sys.stderr)
        return 2

    if args.streaming:
        if args.fileformat != 0:
            print("error: --streaming supports fileformat 0 (libsvm-dense)",
                  file=sys.stderr)
            return 2
        from libskylark_tpu.cli import read_streaming

        X, Y = read_streaming(args.trainfile, args.batch_rows)
    else:
        X, Y = read_dataset(args.trainfile, args.fileformat)
    d = X.shape[1]
    context = Context(seed=args.seed)
    loss = _make_loss(args)
    reg = _make_regularizer(args)

    if args.randomfeatures:
        kernel = _make_kernel(args, d)
        tag = "fast" if args.usefast else (
            "quasi" if args.usequasi else "regular")
        solver = BlockADMMSolver.from_kernel(
            context, loss, reg, args.lam, args.randomfeatures, kernel,
            tag=tag, num_partitions=args.numfeaturepartitions,
        )
    else:
        solver = BlockADMMSolver(
            loss, reg, args.lam, d,
            num_partitions=args.numfeaturepartitions,
        )
    solver.rho = args.rho
    solver.maxiter = args.MAXITER
    solver.tol = args.tolerance
    solver.cache_transforms = args.cachetransforms

    Xv = Yv = None
    if args.valfile:
        Xv, Yv = read_dataset(args.valfile, args.fileformat)

    Yn = np.asarray(Y)
    classes = None
    if not args.regression:
        # recode labels to 0..k-1 (the reference's coding layer); the
        # coding is stored in the model so predictions decode back
        classes = np.unique(Yn)
        Yn = np.searchsorted(classes, Yn)
        if Yv is not None:
            Yv = np.asarray(Yv)
            unknown = np.setdiff1d(np.unique(Yv), classes)
            if unknown.size:
                print(f"error: validation labels {unknown.tolist()} not in "
                      f"training labels", file=sys.stderr)
                return 2
            Yv = np.searchsorted(classes, Yv)

    t0 = time.time()
    model = solver.train(
        X if not hasattr(X, "todense") else X.todense(),
        Yn, Xv=Xv if Xv is None or not hasattr(Xv, "todense")
        else Xv.todense(),
        Yv=Yv, regression=args.regression, verbose=True,
        checkpoint=args.checkpoint_dir or None,
        checkpoint_every=args.checkpoint_every,
    )
    print(f"Training took {time.time() - t0:.2e} sec")
    if classes is not None:
        model.label_coding = classes.tolist()
    model.save(modelfile, header="trained by skylark_ml (libskylark_tpu)")
    print(f"Model saved to {modelfile}")
    return 0


def _test(args) -> int:
    import numpy as np

    from libskylark_tpu.cli import read_dataset
    from libskylark_tpu.ml.metrics import classification_accuracy, rmse
    from libskylark_tpu.ml.model import HilbertModel

    modelfile = args.modelfile or args.modelfile_pos
    model = HilbertModel.load(modelfile)
    X, Y = read_dataset(args.testfile, args.fileformat)
    Xd = X.todense() if hasattr(X, "todense") else X
    labels, decisions = model.predict(Xd)
    labels = np.asarray(labels)
    Yn = np.asarray(Y)
    if not model.regression and model.num_outputs > 1:
        if model.label_coding is not None:
            # decode class indices back to the original training labels
            labels = np.asarray(model.label_coding)[labels.ravel()]
        else:
            # legacy model file without a stored coding: best effort —
            # recode the test labels to 0..k-1; only correct when the test
            # file contains exactly the training label set
            print("warning: model has no label coding; assuming the test "
                  "file's label set equals the training set", file=sys.stderr)
            Yn = np.searchsorted(np.unique(Yn), Yn)
    if args.outputfile:
        out = np.asarray(decisions) if args.decisionvals else labels
        np.savetxt(args.outputfile + ".txt", out, fmt="%.8g")
    if model.regression:
        print(f"RMSE = {rmse(labels, Yn):.6f}")
    else:
        print(f"Accuracy = {classification_accuracy(labels, Yn):.2f} %")
    return 0


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    if args.testfile:
        return _test(args)
    if not args.trainfile:
        print("error: trainfile required in training mode", file=sys.stderr)
        return 2
    return _train(args)


if __name__ == "__main__":
    sys.exit(main())
