"""skylark_svd: approximate SVD of a matrix read from file.

TPU-native analog of ref: nla/skylark_svd.cpp:225-345 — reads libsvm
(file or directory) or an arc-list graph, runs ApproximateSVD (or the
symmetric variant), writes prefix.U.txt / prefix.S.txt / prefix.V.txt.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_svd",
        description="Sketch-accelerated approximate SVD "
        "(ref: nla/skylark_svd.cpp)",
    )
    p.add_argument("inputfile", nargs="?", help="input file (libsvm format)")
    p.add_argument("--filetype", choices=["LIBSVM", "ARC_LIST"],
                   default="LIBSVM")
    p.add_argument("-d", "--directory", action="store_true",
                   help="inputfile is a directory of libsvm shards")
    p.add_argument("-s", "--seed", type=int, default=38734)
    p.add_argument("-k", "--rank", type=int, default=6)
    p.add_argument("-i", "--powerits", type=int, default=2)
    p.add_argument("--skipqr", action="store_true")
    p.add_argument("-r", "--ratio", type=int, default=2,
                   help="oversampling ratio")
    p.add_argument("-a", "--additive", type=int, default=0,
                   help="oversampling additive")
    p.add_argument("--symmetric", action="store_true")
    p.add_argument("--sparse", action="store_true",
                   help="load the matrix as sparse")
    p.add_argument("--single", action="store_true",
                   help="single precision (f32 is the TPU-native default; "
                   "flag kept for command-line parity)")
    from libskylark_tpu.cli import add_streaming_args

    add_streaming_args(p)
    p.add_argument("--profile", nargs=2, type=int, metavar=("H", "W"),
                   help="generate a random HxW matrix and run on it")
    p.add_argument("--prefix", default="out")
    return p


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    import jax.numpy as jnp
    import numpy as np

    import libskylark_tpu.io as skio
    from libskylark_tpu.base.context import Context
    from libskylark_tpu.cli import write_ascii_matrix
    from libskylark_tpu.nla.svd import (
        ApproximateSVDParams,
        approximate_svd,
        approximate_symmetric_svd,
    )

    if args.streaming and (args.directory or args.filetype == "ARC_LIST"
                           or args.sparse or args.profile):
        print("error: --streaming applies only to a single dense libsvm "
              "file", file=sys.stderr)
        return 2

    context = Context(seed=args.seed)
    t0 = time.time()
    if args.profile:
        h, w = args.profile
        rng = np.random.default_rng(args.seed)
        A = jnp.asarray(rng.standard_normal((h, w)).astype(np.float32))
    elif args.inputfile is None:
        print("error: inputfile required (or --profile)", file=sys.stderr)
        return 2
    elif args.filetype == "ARC_LIST":
        # sparse adjacency operand — never densified (ref: the sparse
        # branch of nla/skylark_svd.cpp:129-215)
        A = skio.read_arc_list(args.inputfile, symmetrize=True)
    elif args.directory:
        X, _ = skio.read_dir_libsvm(args.inputfile, sparse=args.sparse)
        A = X if args.sparse else jnp.asarray(X)
    elif args.streaming:
        from libskylark_tpu.cli import read_streaming

        A, _ = read_streaming(args.inputfile, args.batch_rows)
    else:
        X, _ = skio.read_libsvm(args.inputfile, sparse=args.sparse)
        A = X if args.sparse else jnp.asarray(X)
    print(f"Reading the matrix... took {time.time() - t0:.2e} sec")

    params = ApproximateSVDParams(
        num_iterations=args.powerits,
        oversampling_ratio=args.ratio,
        oversampling_additive=args.additive,
        skip_qr=args.skipqr,
    )
    t0 = time.time()
    if args.symmetric or args.filetype == "ARC_LIST":
        V, S = approximate_symmetric_svd(A, args.rank, context, params)
        U = V
    else:
        U, S, V = approximate_svd(A, args.rank, context, params)
    print(f"Computing approximate SVD... took {time.time() - t0:.2e} sec")

    write_ascii_matrix(args.prefix + ".U.txt", U)
    write_ascii_matrix(args.prefix + ".S.txt", S)
    write_ascii_matrix(args.prefix + ".V.txt", V)
    return 0


if __name__ == "__main__":
    sys.exit(main())
