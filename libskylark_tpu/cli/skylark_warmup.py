"""skylark_warmup: build / inspect / verify warmup packs.

The deployment half of the zero-recompile fleet boot
(docs/performance, "Persistent AOT artifacts & warmup packs"):

``build``
    Select the top-N hot serve buckets — from the tune plan cache and
    optionally a serve-stats JSON (telemetry snapshot or
    ``SKYLARK_ENGINE_STATS_DUMP`` artifact) — or take explicit
    ``--spec`` JSON bucket specs, precompile every (bucket, capacity)
    executable, and serialize the pack (artifacts + ``pack.json``
    manifest) into ``--pack``.
``inspect``
    Print the manifest summary and whether THIS host/runtime would
    accept the pack (compat probe + plan-fingerprint check).
``verify``
    Actually load the pack into this process and report the loader's
    counts — a booted replica should see ``loaded == entries`` and
    zero backend compiles.

Examples::

    skylark_warmup build --pack /var/skylark/pack --top 8 \\
        --stats /var/skylark/engine_stats.json
    skylark_warmup build --pack pack --spec '{"endpoint": \\
        "sketch_apply", "family": "JLT", "n": 128, "m": 64, \\
        "s_dim": 32, "rowwise": true, "capacities": [1, 8, 16]}'
    skylark_warmup inspect --pack /var/skylark/pack
    skylark_warmup verify --pack /var/skylark/pack
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="skylark_warmup",
        description="Warmup packs: precompiled serve-bucket bundles "
                    "for zero-recompile fleet boot (docs/performance)")
    sub = p.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="precompile + serialize a pack")
    b.add_argument("--pack", required=True,
                   help="pack directory (created if missing)")
    b.add_argument("--top", type=int, default=8,
                   help="top-N buckets from the tune plan cache "
                        "(ignored when --spec is given)")
    b.add_argument("--stats", default=None,
                   help="serve-stats JSON (telemetry snapshot or "
                        "dump_stats artifact) ranking hot capacity "
                        "classes for selection")
    b.add_argument("--spec", action="append", default=[],
                   help="explicit bucket spec as JSON (repeatable); "
                        "see engine.warmup.BucketSpec")
    b.add_argument("--pad-floor", type=int, default=None)

    for name, hlp in (("inspect", "manifest summary + compat probe"),
                      ("verify", "load the pack into this process")):
        s = sub.add_parser(name, help=hlp)
        s.add_argument("--pack", required=True)

    bp = sub.add_parser(
        "boot-probe",
        help="boot a fresh serving process from the pack (or cold with "
             "--no-load), serve every packed bucket's canonical cohort, "
             "and report compiles/loads/bit-equality/time-to-first-"
             "result — the bench --boot child and the CI boot gate")
    bp.add_argument("--pack", required=True)
    bp.add_argument("--no-load", action="store_true",
                    help="cold side of the A/B: serve the same cohorts "
                         "without loading the pack")
    return p


def _load_stats(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    # accept a dump_stats artifact ({"serve": {...}}), a telemetry
    # snapshot ({"collectors": {"serve": {...}}}), or a bare block
    if "batch_capacity_hist" in doc:
        return doc
    if isinstance(doc.get("serve"), dict):
        return doc["serve"]
    coll = doc.get("collectors")
    if isinstance(coll, dict) and isinstance(coll.get("serve"), dict):
        return coll["serve"]
    return {}


def _cmd_build(args) -> int:
    from libskylark_tpu.engine import warmup

    if args.spec:
        specs = [warmup.BucketSpec.from_dict(json.loads(s))
                 for s in args.spec]
    else:
        stats = _load_stats(args.stats) if args.stats else None
        specs = warmup.select_top_buckets(args.top, stats=stats)
        if not specs:
            print("no serve buckets found in the tune plan cache; "
                  "pass explicit --spec JSON (see docs/performance)",
                  file=sys.stderr)
            return 2
    manifest = warmup.build_pack(args.pack, specs,
                                 pad_floor=args.pad_floor)
    missing = [e["digest"] for e in manifest["entries"]
               if e.get("artifact_missing")]
    print(json.dumps({
        "pack": args.pack,
        "entries": len(manifest["entries"]),
        "plan_fingerprint": manifest["plan_fingerprint"],
        "compat": manifest["compat"],
        "artifact_missing": missing,
    }, indent=1))
    return 1 if missing else 0


def _cmd_inspect(args) -> int:
    from libskylark_tpu.engine import aot, warmup

    try:
        manifest = warmup.read_manifest(args.pack)
    except Exception as e:  # noqa: BLE001 — CLI reports, not raises
        print(f"error: unreadable manifest: {e!r}", file=sys.stderr)
        return 2
    ok, why = aot.compat_probe(manifest.get("compat"))
    from libskylark_tpu import engine

    fp = engine.plan_fingerprint()
    print(json.dumps({
        "schema": manifest.get("schema"),
        "entries": [
            {k: e.get(k) for k in ("name", "endpoint", "capacity",
                                   "kernel", "digest")}
            for e in manifest.get("entries", ())
        ],
        "compat_ok_here": ok,
        "compat_reason": why,
        "plan_fingerprint": manifest.get("plan_fingerprint"),
        "plan_fingerprint_here": fp,
        "plan_fingerprint_match":
            fp == manifest.get("plan_fingerprint"),
    }, indent=1))
    return 0 if ok else 1


def _cmd_verify(args) -> int:
    from libskylark_tpu import engine
    from libskylark_tpu.engine import warmup

    report = warmup.load_pack(args.pack)
    s = engine.stats()
    report["aot_loads"] = s.aot_loads
    report["load_seconds"] = round(s.load_seconds, 4)
    report["backend_compiles"] = s.compiles
    print(json.dumps(report, indent=1))
    ok = (report["skipped"] is None and report["failed"] == 0
          and report["loaded"] == report["entries"])
    return 0 if ok else 1


def _cmd_boot_probe(args) -> int:
    import time

    from libskylark_tpu.base import env as _env
    from libskylark_tpu.engine import warmup

    report = warmup.serve_probe(args.pack, load=not args.no_load)
    # wall time since the parent spawned us (SKYLARK_BOOT_T0 = parent's
    # time.time() at spawn): the honest time-to-first-result including
    # interpreter + jax import — what a cold autoscaled replica pays
    t0 = _env.BOOT_T0.get()
    if t0 is not None:
        report["wall_since_spawn_s"] = round(time.time() - t0, 4)
    print("BOOT_PROBE " + json.dumps(report))
    ok = report["bit_equal"]
    if not args.no_load:
        # a pack that loaded partially (or not at all) still serves —
        # via the compile path — but the probe must not certify it:
        # `boot-probe && deploy` would ship a pack that recompiles on
        # every replica
        w = report["warmup"] or {}
        ok = (ok and w.get("skipped") is None and not w.get("failed")
              and (w.get("loaded", 0) + w.get("resident", 0)
                   == w.get("entries", -1)))
    return 0 if ok else 1


def main(argv=None) -> int:
    from libskylark_tpu.cli import honor_platform_env

    honor_platform_env()
    args = build_parser().parse_args(argv)
    if args.cmd == "build":
        return _cmd_build(args)
    if args.cmd == "inspect":
        return _cmd_inspect(args)
    if args.cmd == "boot-probe":
        return _cmd_boot_probe(args)
    return _cmd_verify(args)


if __name__ == "__main__":
    sys.exit(main())
