"""Distributed sketching over row-sharded data — failure handling as
the design center (docs/distributed).

The source library's entire premise is *distributed* RandNLA (MPI +
Elemental, VC★/★VR row distributions — PAPER.md); this package is
that heritage rebuilt on the repo's own serving substrate, exploiting
the fault-tolerance gift the reference never used: sketching
linearity makes a row shard a **recomputable, idempotent unit of
work**, and a permanently lost shard still leaves a valid sketch of
the surviving rows whose coverage is *reported*, never silent.

- :mod:`~libskylark_tpu.dist.plan` — :class:`ShardPlan` (numbered
  row-range shard tasks whose operator slices are pure positional
  functions of the plan seed: re-execution anywhere is bit-equal),
  range-readable :class:`ShardSource` descriptors (in-memory rows,
  HDF5, libsvm/line streams with resume-at-consumed-offset ingest),
  the canonical deterministic merge tree, and the
  coverage-quantified results (:class:`DistSketchResult` /
  :class:`DegradedSketchResult`).
- :mod:`~libskylark_tpu.dist.coordinator` —
  :class:`DistSketchCoordinator`: dispatch across a
  :class:`~libskylark_tpu.fleet.ReplicaPool` with ring-preference
  placement, retry + reassignment under ``SKYLARK_DIST_RETRIES``,
  straggler hedging, and the ``min_coverage`` gate
  (:class:`~libskylark_tpu.base.errors.SketchCoverageError`).
- :mod:`~libskylark_tpu.dist.algorithms` — distributed randomized SVD
  and sketched least-squares whose only cross-host traffic is the
  merged sketch.
- :mod:`~libskylark_tpu.dist.serve` — the pipelined serve tier:
  :class:`DistServeJob` behind the ``submit_dist_sketch`` /
  ``submit_dist_lstsq`` / ``submit_dist_svd`` endpoints of
  :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor` and
  :class:`~libskylark_tpu.fleet.Router` — incremental canonical
  merging, per-QoS-class ``min_coverage`` gates with interactive
  early resolve, tenant-billed retries/hedges, and content-addressed
  caching of whole distributed jobs.

Chaos-replayed by ``benchmarks/chaos_battery.py`` (the ``dist.shard``
/ ``dist.ingest`` / ``dist.merge`` fault sites) and CI-gated by
``benchmarks/dist_smoke.py`` and ``benchmarks/dist_serve_smoke.py``
(a SIGKILLed process replica mid-storm: every shard reassigned, the
merge bit-equal to the one-shot reference).
"""

from libskylark_tpu.dist.algorithms import (lstsq_plan, randomized_svd,
                                            sketched_lstsq, svd_plan)
from libskylark_tpu.dist.coordinator import (DistSketchCoordinator,
                                             dist_stats)
from libskylark_tpu.dist.plan import (ArraySource, DegradedSketchResult,
                                      DistSketchResult, HDF5Source,
                                      LibsvmSource, ShardPlan,
                                      ShardSource, merge_partials,
                                      sketch_local)
from libskylark_tpu.dist.serve import (DistServeJob, IncrementalMerger,
                                       class_min_coverage,
                                       dist_request_digest,
                                       dist_serve_stats)

__all__ = [
    "ArraySource", "DegradedSketchResult", "DistServeJob",
    "DistSketchCoordinator", "DistSketchResult", "HDF5Source",
    "IncrementalMerger", "LibsvmSource", "ShardPlan", "ShardSource",
    "class_min_coverage", "dist_request_digest", "dist_serve_stats",
    "dist_stats", "lstsq_plan", "merge_partials", "randomized_svd",
    "sketch_local", "sketched_lstsq", "svd_plan",
]
