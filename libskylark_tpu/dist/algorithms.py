"""Sketch-size-communication algorithms over the distributed sketch.

The libSkylark heritage at pod scale (ROADMAP item 2): randomized SVD
and sketched least-squares whose ONLY cross-host traffic is the
merged ``s_dim × d`` sketch — each replica streams its own row shards
(or receives just its shard's rows) and returns a partial sketch;
communication is proportional to sketch size, not data size. Both
entry points ride the full fault-tolerance contract: retried shard
tasks, quantified degraded merges, the ``min_coverage`` gate.

The plan builders (:func:`svd_plan`, :func:`lstsq_plan`) are shared
with the pipelined serve endpoints (``submit_dist_svd`` /
``submit_dist_lstsq`` — :mod:`libskylark_tpu.dist.serve`), so the
library call and the serve request of the same arguments sketch the
same plan, hence the same bits and the same cache digest.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.dist import plan as _plan
from libskylark_tpu.dist.coordinator import DistSketchCoordinator


def svd_plan(source: _plan.ShardSource, rank: int, *,
             s_dim: Optional[int] = None, seed: int = 0,
             kind: str = "jlt", shard_rows: int = 0) -> _plan.ShardPlan:
    """The validated :class:`~libskylark_tpu.dist.plan.ShardPlan` of a
    distributed randomized SVD: additive row sketch at
    ``s_dim or max(2·rank, rank+8)`` (clamped to ``source.n``)."""
    if rank < 1:
        raise errors.InvalidParametersError(
            f"rank must be >= 1, got {rank}")
    s = int(s_dim) if s_dim else max(2 * int(rank), int(rank) + 8)
    if kind not in _plan.ADDITIVE_KINDS:
        raise errors.InvalidParametersError(
            f"randomized_svd needs an additive sketch kind, got {kind!r}")
    return _plan.ShardPlan(kind=kind, n=source.n,
                           s_dim=min(s, source.n), d=source.d,
                           seed=seed, shard_rows=shard_rows).validate()


def lstsq_plan(source: _plan.ShardSource, *, s_dim: int, seed: int = 0,
               kind: str = "cwt",
               shard_rows: int = 0) -> _plan.ShardPlan:
    """The validated joint-sketch plan of a distributed sketched
    least-squares solve: the source must carry targets (``Y``)."""
    if source.targets < 1:
        raise errors.InvalidParametersError(
            "sketched_lstsq needs a source with targets (Y rows)")
    if kind not in _plan.ADDITIVE_KINDS:
        raise errors.InvalidParametersError(
            f"sketched_lstsq needs an additive sketch kind, got {kind!r}")
    return _plan.ShardPlan(kind=kind, n=source.n,
                           s_dim=min(int(s_dim), source.n),
                           d=source.d, seed=seed,
                           targets=source.targets,
                           shard_rows=shard_rows).validate()


def _run(plan: _plan.ShardPlan, source: _plan.ShardSource,
         coordinator: Optional[DistSketchCoordinator],
         min_coverage: Optional[float]) -> _plan.DistSketchResult:
    if coordinator is None:
        result = _plan.sketch_local(plan, source)
        gate = 1.0 if min_coverage is None else float(min_coverage)
        return result.require(gate)
    return coordinator.sketch(plan, source, min_coverage=min_coverage)


def randomized_svd(source: _plan.ShardSource, rank: int, *,
                   s_dim: Optional[int] = None, seed: int = 0,
                   kind: str = "jlt", shard_rows: int = 0,
                   coordinator: Optional[DistSketchCoordinator] = None,
                   min_coverage: Optional[float] = None) -> dict:
    """Distributed one-pass randomized SVD of a row-sharded dataset:
    merge the ``s_dim × d`` row sketch, then factor the small sketch
    locally (the streaming-rSVD math of the ``isvd`` sessions, fed by
    shard tasks instead of appends). Returns ``singular_values``,
    ``Vt`` (top ``rank``), plus the merge's exact ``coverage`` and
    ``missing`` ranges — a degraded merge above ``min_coverage``
    yields the SVD *of the surviving rows' sketch*, labeled as such."""
    plan = svd_plan(source, rank, s_dim=s_dim, seed=seed, kind=kind,
                    shard_rows=shard_rows)
    res = _run(plan, source, coordinator, min_coverage)
    import jax.numpy as jnp

    _, sv, Vt = jnp.linalg.svd(jnp.asarray(res.SX), full_matrices=False)
    k = min(int(rank), plan.s_dim, plan.d)
    return {"singular_values": np.asarray(sv[:k]),
            "Vt": np.asarray(Vt[:k]),
            "coverage": res.coverage, "missing": list(res.missing),
            "degraded": res.degraded}


def sketched_lstsq(source: _plan.ShardSource, *,
                   s_dim: int, seed: int = 0, kind: str = "cwt",
                   shard_rows: int = 0,
                   coordinator: Optional[DistSketchCoordinator] = None,
                   min_coverage: Optional[float] = None) -> dict:
    """Distributed sketch-and-solve least squares
    ``min_w ||X w − Y||``: merge the joint ``(S·X, S·Y)`` sketch off
    the row shards, solve the small ``s_dim × d`` problem locally.
    The source must carry targets (``Y``). Returns ``coef`` (d ×
    targets) plus the coverage accounting."""
    plan = lstsq_plan(source, s_dim=s_dim, seed=seed, kind=kind,
                      shard_rows=shard_rows)
    res = _run(plan, source, coordinator, min_coverage)
    import jax.numpy as jnp

    w, *_ = jnp.linalg.lstsq(jnp.asarray(res.SX), jnp.asarray(res.SY))
    return {"coef": np.asarray(w),
            "coverage": res.coverage, "missing": list(res.missing),
            "degraded": res.degraded}


__all__ = ["lstsq_plan", "randomized_svd", "sketched_lstsq", "svd_plan"]
