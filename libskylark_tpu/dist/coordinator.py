"""DistSketchCoordinator: fault-tolerant shard-task dispatch.

The coordinator turns a :class:`~libskylark_tpu.dist.plan.ShardPlan`
into shard tasks and drives them across a
:class:`~libskylark_tpu.fleet.ReplicaPool` (thread or process
replicas — the latter are real preemption domains a ``crash`` fault or
a SIGKILL can take out mid-storm), with failure handling as the design
center:

- **deterministic placement**: shard ``i`` hashes onto the fleet's
  consistent-hash ring at ``(plan fingerprint, i)``; the ring's
  preference order is the failover sequence, so a retry lands on a
  deterministic next replica (``dist.shards_reassigned``);
- **retries are re-executions**: a shard task is idempotent (its
  partial is a pure function of the plan — :mod:`~libskylark_tpu.dist.
  plan`), so a failed/crashed attempt is simply recomputed under the
  ``SKYLARK_DIST_RETRIES`` budget; a replica that died out from under
  its tasks (pipe EOF → ``ServeOverloadedError`` futures) looks like
  any other failed attempt;
- **stragglers are mirrored**: with ``SKYLARK_DIST_HEDGE`` on, a shard
  unresolved past ``SKYLARK_DIST_HEDGE_DELAY_MS`` is dispatched again
  to the next preference replica and the first completed result wins —
  safe because both compute identical bits (the r15 hedging discipline
  applied to shard tasks);
- **loss is gated, never silent**: shards that exhaust the budget are
  abandoned (``dist.shards_abandoned``) and the merge returns a
  :class:`~libskylark_tpu.dist.plan.DegradedSketchResult` carrying the
  exact coverage — if coverage falls below the caller's
  ``min_coverage`` (default ``SKYLARK_DIST_MIN_COVERAGE``) the
  coordinator raises :class:`~libskylark_tpu.base.errors.
  SketchCoverageError` instead.

Cross-replica traffic is proportional to *sketch* size, not data
size: a task ships a plan + a source descriptor (or the shard's rows
for in-memory sources) and returns an ``s_dim × d`` partial; the data
itself never aggregates anywhere.
"""

from __future__ import annotations

import time
from concurrent.futures import FIRST_COMPLETED, Future, wait
from typing import Dict, List, Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.dist import plan as _plan
from libskylark_tpu.resilience.policy import Deadline
from libskylark_tpu.telemetry import metrics as _metrics

# Unified-registry instruments (docs/observability): declared in
# telemetry/names.py, created here once, rendered to Prometheus by the
# exporter; the "dist" collector below carries the process-lifetime
# rollup into every benchmarks snapshot even with telemetry off.
_DISPATCHED = _metrics.counter(
    "dist.shards_dispatched",
    "Shard-task dispatches (first attempts, retries and hedges)")
_RETRIED = _metrics.counter(
    "dist.shards_retried", "Shard-task re-executions after a failure")
_REASSIGNED = _metrics.counter(
    "dist.shards_reassigned",
    "Shard retries that moved to a different replica")
_ABANDONED = _metrics.counter(
    "dist.shards_abandoned",
    "Shards that exhausted their retry budget (degraded merges)")
_MERGES = _metrics.counter(
    "dist.merges", "Partial-sketch merges performed")
_COVERAGE = _metrics.gauge(
    "dist.coverage", "Coverage fraction of the most recent merge")

_LIFE_LOCK = _locks.make_lock("dist.lifetime")
_LIFE = {"dispatched": 0, "retried": 0, "reassigned": 0,
         "abandoned": 0, "hedged": 0, "merges": 0,
         "last_coverage": None}


def _life(**deltas) -> None:
    with _LIFE_LOCK:
        for k, v in deltas.items():
            if k == "last_coverage":
                _LIFE[k] = v
            else:
                _LIFE[k] += v


def dist_stats() -> dict:
    """Process-lifetime distributed-sketching rollup (the ``dist``
    telemetry collector)."""
    with _LIFE_LOCK:
        return dict(_LIFE)


_metrics.register_collector("dist", dist_stats)


class _Attempt:
    __slots__ = ("index", "future", "replica", "attempt", "t0", "hedge")

    def __init__(self, index, future, replica, attempt, hedge=False):
        self.index = index
        self.future = future
        self.replica = replica
        self.attempt = attempt
        self.t0 = time.monotonic()
        self.hedge = hedge


class DistSketchCoordinator:
    """Dispatch/retry/merge driver over a replica fleet (module doc).

    ``pool`` is a :class:`~libskylark_tpu.fleet.ReplicaPool` (live
    membership — crash-reaped members leave the candidate set);
    ``replicas`` an explicit list of replica objects for embedding/
    tests. With neither, every shard computes locally in dispatch
    order — :func:`~libskylark_tpu.dist.plan.sketch_local` semantics
    with the same retry accounting.

    ``max_inflight`` bounds concurrently outstanding shard tasks
    (default ``2 ×`` fleet size; memory bound = inflight × partial
    size) — hedge mirrors count against the same bound, so a
    saturated window defers mirroring until a slot frees (and
    ``max_inflight=1`` effectively disables hedging).
    ``max_inflight=1`` serializes dispatch — the chaos battery uses
    it to make the ``dist.shard`` fired sequence deterministic.
    """

    def __init__(self, pool=None, *, replicas: Optional[List] = None,
                 retries: Optional[int] = None,
                 min_coverage: Optional[float] = None,
                 hedge: Optional[bool] = None,
                 hedge_delay_s: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 vnodes: int = 64):
        from libskylark_tpu.fleet.ring import HashRing

        if pool is not None and replicas is not None:
            raise errors.InvalidParametersError(
                "pass a pool OR explicit replicas, not both")
        self._pool = pool
        self._replicas = ({r.name: r for r in replicas}
                          if replicas else None)
        self._vnodes = int(vnodes)
        self._ring = HashRing(self._names(), vnodes=self._vnodes)
        self.retries = (int(_env.DIST_RETRIES.get())
                        if retries is None else int(retries))
        self.min_coverage = (float(_env.DIST_MIN_COVERAGE.get())
                             if min_coverage is None
                             else float(min_coverage))
        self.hedge = (bool(_env.DIST_HEDGE.get())
                      if hedge is None else bool(hedge))
        self.hedge_delay_s = (
            float(_env.DIST_HEDGE_DELAY_MS.get()) / 1000.0
            if hedge_delay_s is None else float(hedge_delay_s))
        self._max_inflight = max_inflight
        self._lock = _locks.make_lock("dist.coordinator")
        self._stats = {"dispatched": 0, "retried": 0, "reassigned": 0,
                       "abandoned": 0, "hedged": 0, "merges": 0,
                       "last_coverage": None, "by_replica": {}}

    # -- membership -----------------------------------------------------

    def _names(self) -> List[str]:
        if self._pool is not None:
            return list(self._pool.names())
        if self._replicas is not None:
            return list(self._replicas)
        return []

    def _get(self, name: str):
        if self._pool is not None:
            return self._pool.get(name)
        return self._replicas[name]

    def _live_names(self) -> List[str]:
        out = []
        for name in self._names():
            try:
                if self._get(name).state() not in ("STOPPED",
                                                   "DRAINING"):
                    out.append(name)
            except Exception:  # noqa: BLE001 — reaped mid-iteration
                continue
        return out

    def _sync_ring(self) -> List[str]:
        """Fold live membership into the ring (crash-reaped members
        leave; autoscaled arrivals join) and return it."""
        live = self._live_names()
        for name in set(self._ring.members()) - set(live):
            self._ring.remove(name)
        for name in live:
            self._ring.add(name)
        return live

    def _candidates(self, fingerprint: str, index: int,
                    avoid=()) -> List[str]:
        """Deterministic placement/failover order of shard ``index``:
        ring preference at ``(plan fingerprint, index)``, members the
        attempt history says to avoid rotated to the tail."""
        live = self._sync_ring()
        if not live:
            return []
        pref = list(self._ring.preference((fingerprint, index)))
        avoid = [a for a in avoid if a in pref]
        return [n for n in pref if n not in avoid] + list(avoid)

    # -- the storm ------------------------------------------------------

    def sketch(self, plan: _plan.ShardPlan, source: _plan.ShardSource,
               *, min_coverage: Optional[float] = None,
               deadline=None) -> _plan.DistSketchResult:
        """Run the full shard storm and merge.

        Returns a full-coverage :class:`DistSketchResult` (bit-equal
        to :func:`~libskylark_tpu.dist.plan.sketch_local` of the same
        plan+source) or, when shards were abandoned, a
        :class:`DegradedSketchResult` — gated by ``min_coverage``
        (default: the coordinator's, default
        ``SKYLARK_DIST_MIN_COVERAGE``). Logic errors (bad plan/source)
        propagate immediately; everything else is retried/abandoned
        per the budget."""
        plan.validate()
        if source.n < plan.n:
            raise errors.InvalidParametersError(
                f"source holds {source.n} rows < plan.n={plan.n}")
        gate = (self.min_coverage if min_coverage is None
                else float(min_coverage))
        deadline = Deadline.coerce(deadline)
        pending = [i for i, _, _ in plan.shards()]
        tried: Dict[int, List[str]] = {i: [] for i in pending}
        attempts: Dict[int, int] = {i: 0 for i in pending}
        last_ran: Dict[int, str] = {}     # replica of the last ACCEPTED
        #                                   attempt (reassignment truth)
        inflight: Dict[Future, _Attempt] = {}
        settled: Dict[int, dict] = {}
        abandoned: List[int] = []
        hedged: set = set()
        cap = self._max_inflight or max(2, 2 * max(1, len(self._names())))
        # invariant for the whole storm — compute once, not per attempt
        plan_doc = plan.to_dict()
        fingerprint = plan.fingerprint()

        def task_payload(index: int) -> dict:
            lo, hi = plan.shard_range(index)
            return {"plan": plan_doc, "index": index,
                    "source": _plan.source_to_wire(
                        source.subrange(lo, hi))}

        def dispatch(index: int, *, hedge: bool = False,
                     exclude: Optional[str] = None) -> bool:
            """One attempt; False when no replica accepted (counts as
            a failed attempt for the budget). ``exclude`` drops a
            member outright (a hedge mirror must not land on the very
            replica whose slowness triggered it)."""
            cands = self._candidates(fingerprint, index,
                                     avoid=tried[index])
            if exclude is not None:
                cands = [n for n in cands if n != exclude]
            for name in cands:
                try:
                    fut = self._get(name).shard(task_payload(index))
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — a refusal
                    # of ANY class (dead member KeyError, overload,
                    # pipe loss, an unpicklable payload) is one failed
                    # candidate, never an uncaught storm crash; logic
                    # errors still fail fast below
                    if not _retryable(e):
                        raise
                    if name not in tried[index]:
                        tried[index].append(name)
                    continue
                prev = last_ran.get(index)
                last_ran[index] = name
                if name not in tried[index]:
                    tried[index].append(name)
                att = _Attempt(index, fut, name,
                               attempts[index], hedge=hedge)
                inflight[fut] = att
                self._account("dispatched", name)
                if not hedge and attempts[index] > 0:
                    self._account("retried", name)
                    if prev is not None and prev != name:
                        self._account("reassigned", name)
                return True
            if not cands and self._pool is None \
                    and self._replicas is None:
                # no fleet: compute here, now (sketch_local semantics
                # with the same retry/abandon accounting)
                fut: Future = Future()
                att = _Attempt(index, fut, "<local>", attempts[index],
                               hedge=hedge)
                inflight[fut] = att
                self._account("dispatched", "<local>")
                if not hedge and attempts[index] > 0:
                    self._account("retried", "<local>")
                try:
                    fut.set_result(_plan.execute_task(
                        task_payload(index)))
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001
                    fut.set_exception(e)
                return True
            return False

        def note_failure(index: int, exc: Optional[BaseException]
                         ) -> None:
            if exc is not None and not _retryable(exc):
                raise exc
            attempts[index] += 1
            if attempts[index] > self.retries:
                abandoned.append(index)
                self._account("abandoned", None)
            else:
                # the retried attempt is a fresh straggler candidate
                hedged.discard(index)
                pending.append(index)

        # refused-dispatch pacing: when NO replica accepts (a fleet
        # momentarily empty — the last member crashed and its
        # autoscaled replacement is still booting), the budget must
        # not burn in a zero-delay spin; each refusal pass sleeps a
        # growing, bounded delay so a recovering fleet gets its shot
        # before shards are abandoned
        refusal_streak = 0
        while pending or inflight:
            if deadline is not None and deadline.expired:
                # out of budget: whatever is unresolved is abandoned —
                # the degraded accounting (and the gate below) reports
                # it rather than hanging past the caller's deadline
                for fut, att in list(inflight.items()):
                    if att.index not in settled \
                            and att.index not in abandoned:
                        abandoned.append(att.index)
                        self._account("abandoned", None)
                inflight.clear()
                for index in pending:
                    if index not in abandoned:
                        abandoned.append(index)
                        self._account("abandoned", None)
                pending = []
                break
            while pending and len(inflight) < cap:
                index = pending.pop(0)
                if index in settled or index in abandoned:
                    continue
                if dispatch(index):
                    refusal_streak = 0
                else:
                    note_failure(index, None)
                    refusal_streak += 1
                    break           # one refusal ends this fill pass
            if not inflight:
                if pending:
                    if refusal_streak:
                        delay = min(0.05 * refusal_streak, 1.0)
                        if deadline is not None:
                            delay = min(delay,
                                        max(deadline.remaining(), 0.0))
                        time.sleep(delay)
                    continue
                break
            # without hedging or a deadline there is no timer to
            # service — block until something completes instead of
            # waking 20x/s for nothing
            poll = (0.05 if self.hedge or deadline is not None
                    else None)
            done, _ = wait(list(inflight), timeout=poll,
                           return_when=FIRST_COMPLETED)
            now = time.monotonic()
            if self.hedge and not done:
                for fut, att in list(inflight.items()):
                    if len(inflight) >= cap:
                        break       # mirrors honor the inflight bound
                    if (not att.hedge and att.index not in hedged
                            and now - att.t0 >= self.hedge_delay_s):
                        # mark only a mirror that actually launched —
                        # a refused hedge leaves the shard eligible
                        # for mirroring on a later tick. The straggling
                        # primary's own replica is excluded outright:
                        # doubling its load is not straggler protection
                        if dispatch(att.index, hedge=True,
                                    exclude=att.replica):
                            hedged.add(att.index)
                            self._account("hedged", None)
            for fut in done:
                # tolerate a future already purged this round: when a
                # hedge pair completes within one wait window, the
                # first-processed winner pops its twin from inflight
                # and the twin still sits in `done`
                att = inflight.pop(fut, None)
                if att is None:
                    continue
                if att.index in settled or att.index in abandoned:
                    continue            # a hedge twin already decided
                exc = fut.exception()
                if exc is None:
                    settled[att.index] = fut.result()["partial"]
                    # stop waiting on hedge twins of a settled shard:
                    # the loser thread finishes in the background and
                    # its (bit-identical) result is simply dropped
                    for f2 in [f for f, a in inflight.items()
                               if a.index == att.index]:
                        inflight.pop(f2)
                else:
                    # a hedge twin may still be running; only charge
                    # the budget when no other attempt is in flight
                    twins = [a for a in inflight.values()
                             if a.index == att.index]
                    if not twins:
                        note_failure(att.index, exc)

        result = self._merge(plan, settled)
        return result.require(gate)

    def _merge(self, plan, settled) -> _plan.DistSketchResult:
        result = _plan.build_result(plan, settled)
        _MERGES.inc()
        _COVERAGE.set(result.coverage)
        _life(merges=1, last_coverage=result.coverage)
        with self._lock:
            self._stats["merges"] += 1
            self._stats["last_coverage"] = result.coverage
        return result

    def _account(self, what: str, replica: Optional[str]) -> None:
        metric = {"dispatched": _DISPATCHED, "retried": _RETRIED,
                  "reassigned": _REASSIGNED, "abandoned": _ABANDONED,
                  "hedged": None}[what]
        if metric is not None:
            if replica is not None:
                metric.inc(replica=replica)
            else:
                metric.inc()
        _life(**{what: 1})
        with self._lock:
            self._stats[what] += 1
            if what == "dispatched" and replica is not None:
                by = self._stats["by_replica"]
                by[replica] = by.get(replica, 0) + 1

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["by_replica"] = dict(out["by_replica"])
            return out


def _retryable(exc: BaseException) -> bool:
    """Whether a shard-task failure is worth re-executing: everything
    except plan/source logic errors (which would fail identically on
    every replica forever) and interpreter-exit signals."""
    if isinstance(exc, (errors.InvalidParametersError,
                        errors.UnsupportedError)):
        return False
    return not isinstance(exc, (KeyboardInterrupt, SystemExit))


__all__ = ["DistSketchCoordinator", "dist_stats"]
