"""Shard plans: distributed sketching as re-executable units of work.

The mathematical foundation (PAPER.md; the same linearity the stateful
sessions exploit across *time*, applied across *space*): sketching
transforms are linear maps, so the sketch of row-sharded data is a
cheap merge of independently computed per-shard partial sketches —
CountSketch/JLT/SRHT partials **add**, sampler (UST) partials
**concatenate** (each output row is owned by exactly one input shard).
That makes a row shard a *recomputable, idempotent unit of work*:

- **re-execution is bit-equal anywhere**: a shard's operator slice is
  a pure positional function of ``(plan seed, row range)`` — the
  counter-based streams (``base/randgen.stream_slice``,
  ``DenseTransform.s_panel``, ``FJLT.operator_panel``) materialize
  exactly the rows ``[lo, hi)`` without generating anything else, so
  any replica (or the same replica after a crash) reproduces the
  partial sketch bit-exactly;
- **merge order is invariant**: :func:`merge_partials` canonicalizes
  to ascending shard index and reduces through a fixed pairwise tree,
  so the merged bits depend only on *which* shards are present, never
  on arrival order or on how a coordinator grouped intermediate
  merges;
- **loss is quantifiable**: a permanently lost shard still leaves a
  valid sketch of the surviving rows; :func:`build_result` reports the
  exact ``coverage`` fraction and the missing row ranges instead of
  returning a silently-partial answer.

Determinism contract: the merged sketch is a pure function of
``(plan, source batch grid, set of merged shard indices)``. Batch
boundaries inside a shard sit on the absolute ``batch_rows`` grid, so
a mid-shard ingest resume (the r9 WebHDFS reconnect-at-offset
discipline promoted to the shard task) re-reads from the consumed
offset and folds bit-identically. The merged result of the *full*
shard set equals :func:`sketch_local` — the one-shot single-process
execution of the same plan — bit for bit, whatever failed and
wherever shards ran; it is ``allclose`` (not bit-equal, floating-point
reassociation) to the one-shot ``transform.apply`` for the additive
kinds, and exactly equal for ``ust``.

Chaos seams (:mod:`libskylark_tpu.resilience.faults`):
``dist.shard`` fires at shard-task execution entry (a ``crash`` spec
here is the deterministic kill -9 of a replica mid-storm),
``dist.ingest`` once per ingested batch (transient ingest failures
resume at the consumed offset), ``dist.merge`` at merge entry.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterator, List, Mapping, Optional, Tuple

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
# one grid, one implementation: the absolute-batch-boundary invariant
# (bit-equal resume) is io/chunked's — every range reader shares it
from libskylark_tpu.io.chunked import grid_spans as _grid_spans
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience.policy import RetryPolicy

KINDS = ("cwt", "jlt", "srht", "ust")

#: kinds whose partials merge by addition (vs ``ust`` placement)
ADDITIVE_KINDS = ("cwt", "jlt", "srht")


def _ingest_retry() -> RetryPolicy:
    """Default policy for mid-shard ingest resume: transient read
    failures back off and re-enter the source at the consumed offset
    (the accumulator is carried — nothing already folded recomputes)."""
    return RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=1.0)


# ---------------------------------------------------------------------------
# the plan: row ranges + transform identity
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """The (pickleable, JSON-able) identity of one distributed sketch:
    everything a replica needs to compute any shard's partial bit-
    exactly. ``n`` is the total row extent, ``s_dim`` the sketch
    dimension, ``d`` the row width, ``seed`` the transform Context
    seed, ``targets`` the Y columns sketched alongside (0: X only).
    ``shard_rows`` pins the rows per shard task (0 defers to
    ``SKYLARK_DIST_SHARD_ROWS``)."""

    kind: str
    n: int
    s_dim: int
    d: int
    seed: int = 0
    dtype: str = "float32"
    targets: int = 0
    shard_rows: int = 0
    replace: bool = True          # ust: sample with replacement

    def validate(self) -> "ShardPlan":
        if self.kind not in KINDS:
            raise errors.InvalidParametersError(
                f"unknown shard-plan kind {self.kind!r}; expected one "
                f"of {KINDS}")
        if self.n < 1 or self.s_dim < 1 or self.d < 1:
            raise errors.InvalidParametersError(
                f"shard-plan dims must be positive, got n={self.n} "
                f"s_dim={self.s_dim} d={self.d}")
        if self.kind == "srht" and self.n & (self.n - 1):
            raise errors.InvalidParametersError(
                f"srht shard plans need n a power of two (WHT length), "
                f"got {self.n}")
        if self.shard_rows < 0 or self.targets < 0:
            raise errors.InvalidParametersError(
                f"shard_rows/targets must be >= 0, got "
                f"{self.shard_rows}/{self.targets}")
        return self

    # -- shard geometry -------------------------------------------------

    @property
    def rows_per_shard(self) -> int:
        return int(self.shard_rows) or int(_env.DIST_SHARD_ROWS.get())

    @property
    def num_shards(self) -> int:
        return -(-self.n // self.rows_per_shard)

    def shard_range(self, index: int) -> Tuple[int, int]:
        """Global row range ``[lo, hi)`` of shard ``index``."""
        if not 0 <= index < self.num_shards:
            raise errors.InvalidParametersError(
                f"shard index {index} out of range "
                f"[0, {self.num_shards})")
        b = self.rows_per_shard
        return index * b, min((index + 1) * b, self.n)

    def shards(self) -> List[Tuple[int, int, int]]:
        """All ``(index, lo, hi)`` shard tasks, in index order."""
        return [(i, *self.shard_range(i)) for i in range(self.num_shards)]

    # -- identity -------------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        # pin the effective shard grid into the serialized identity so
        # a replica under a different SKYLARK_DIST_SHARD_ROWS computes
        # the same ranges
        d["shard_rows"] = self.rows_per_shard
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ShardPlan":
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls)
                      if f.name in d}).validate()

    def fingerprint(self) -> str:
        """Stable digest of the plan — the coordinator's ring-affinity
        key base and the routing identity of every shard task."""
        return hashlib.sha256(
            json.dumps(self.to_dict(), sort_keys=True).encode()
        ).hexdigest()[:16]

    def _transform(self):
        """The global transform this plan shards (lazy, cheap: the
        operator itself is virtual — only stream keys are derived)."""
        from libskylark_tpu.base.context import Context

        ctx = Context(seed=int(self.seed))
        if self.kind == "cwt":
            from libskylark_tpu.sketch.hash import CWT

            return CWT(self.n, self.s_dim, ctx)
        if self.kind == "jlt":
            from libskylark_tpu.sketch.dense import JLT

            return JLT(self.n, self.s_dim, ctx)
        if self.kind == "srht":
            from libskylark_tpu.sketch.fjlt import FJLT

            return FJLT(self.n, self.s_dim, ctx, fut="wht")
        from libskylark_tpu.sketch.ust import UST

        return UST(self.n, self.s_dim, ctx, replace=self.replace)


# ---------------------------------------------------------------------------
# sources: range-readable row streams
# ---------------------------------------------------------------------------




class ShardSource:
    """A row source shard tasks read ranges from. Subclasses are small
    pickleable descriptors (they cross the process-replica pipe);
    ``read(lo, hi)`` yields ``(offset, X, Y)`` batches covering exactly
    ``[lo, hi)`` on the absolute batch grid, re-enterable at any
    previously yielded batch boundary (the ingest-resume seam)."""

    n: int
    d: int
    targets: int = 0

    def read(self, lo: int, hi: int
             ) -> Iterator[Tuple[int, np.ndarray, Optional[np.ndarray]]]:
        raise NotImplementedError

    def subrange(self, lo: int, hi: int) -> "ShardSource":
        """The source a shard task ships with: descriptors return
        ``self`` (the replica reads its own range); in-memory sources
        return just the shard's rows so a task never pickles the whole
        dataset."""
        return self


class ArraySource(ShardSource):
    """In-memory rows. ``batch_rows=0`` (default) reads a requested
    range as one slice; a task dispatched remotely carries only its
    shard's rows (:meth:`subrange`)."""

    def __init__(self, X, Y=None, batch_rows: int = 0, offset: int = 0):
        self._X = np.asarray(X)
        if self._X.ndim != 2:
            raise errors.InvalidParametersError(
                f"ArraySource expects 2-D rows, got {self._X.shape}")
        self._Y = None
        self.targets = 0
        if Y is not None:
            self._Y = np.asarray(Y)
            if self._Y.ndim == 1:
                self._Y = self._Y[:, None]
            if self._Y.shape[0] != self._X.shape[0]:
                raise errors.InvalidParametersError(
                    f"ArraySource: X has {self._X.shape[0]} rows but Y "
                    f"has {self._Y.shape[0]}")
            self.targets = int(self._Y.shape[1])
        self._off = int(offset)           # global row of local row 0
        self.n = self._off + int(self._X.shape[0])
        self.d = int(self._X.shape[1])
        self.batch_rows = int(batch_rows)

    def read(self, lo, hi):
        if lo < self._off or hi > self.n:
            raise errors.InvalidParametersError(
                f"ArraySource holds rows [{self._off}, {self.n}); "
                f"read asked for [{lo}, {hi})")
        for a, b in _grid_spans(lo, hi, self.batch_rows):
            i, j = a - self._off, b - self._off
            yield a, self._X[i:j], (
                self._Y[i:j] if self._Y is not None else None)

    def subrange(self, lo, hi):
        i, j = lo - self._off, hi - self._off
        return ArraySource(self._X[i:j],
                           self._Y[i:j] if self._Y is not None else None,
                           batch_rows=self.batch_rows, offset=lo)


@dataclasses.dataclass
class HDF5Source(ShardSource):
    """Rows from an HDF5 file in the reference's dense ``X``/``Y``
    layout (:mod:`libskylark_tpu.io.hdf5`): every replica range-reads
    its own shard's slices off shared storage — only the path crosses
    the wire. Dims are pinned at construction (:meth:`probe`), so a
    replica never re-probes."""

    path: str
    n: int
    d: int
    targets: int = 1
    batch_rows: int = 4096

    @classmethod
    def probe(cls, path: str, batch_rows: int = 4096) -> "HDF5Source":
        from libskylark_tpu.io.hdf5 import _require_h5py

        h5py = _require_h5py()
        with h5py.File(path, "r") as f:
            n, d = f["X"].shape
            y = f["Y"]
            nt = 1 if y.ndim == 1 else int(y.shape[1])
        return cls(path=path, n=int(n), d=int(d), targets=nt,
                   batch_rows=batch_rows)

    def read(self, lo, hi):
        from libskylark_tpu.io.chunked import iter_hdf5_batches

        at = lo
        for X, Y in iter_hdf5_batches(self.path, self.batch_rows,
                                      start_row=lo, stop_row=hi):
            if Y.ndim == 1:
                Y = Y[:, None]
            yield at, X, Y
            at += len(X)


@dataclasses.dataclass
class LibsvmSource(ShardSource):
    """Rows from a libsvm line stream. ``path`` is a filesystem path
    (re-openable in any replica off shared storage); ``opener`` is an
    optional zero-arg callable returning a fresh line iterable — the
    transport seam, e.g. ``functools.partial(webhdfs_lines, url)`` —
    used instead of the path when given. It must be *pickleable* to
    cross a process pipe: a module-level function or ``partial`` of
    one, never a lambda/closure (an unpicklable opener fails each
    dispatch attempt and the shard degrades into the abandoned
    accounting instead of crashing the storm). Line streams have no random access: a range read parses
    from the top and discards rows before ``lo`` (the reference's
    root-reads-and-scatters discipline); a *resume* after a transient
    failure re-opens the stream and skips to the consumed offset —
    nothing already folded recomputes."""

    path: Optional[str]
    n: int
    d: int
    targets: int = 1
    batch_rows: int = 4096
    opener: Optional[object] = None

    def _lines(self):
        if self.opener is not None:
            return self.opener()
        return self.path

    def read(self, lo, hi):
        from libskylark_tpu.io.chunked import iter_libsvm_batches

        row = 0
        for X, Y in iter_libsvm_batches(self._lines(), self.batch_rows,
                                        d=self.d, max_n=hi):
            m = len(X)
            a, b = max(lo, row), min(hi, row + m)
            if a < b:
                Yb = Y[a - row:b - row]
                if Yb.ndim == 1:
                    Yb = Yb[:, None]
                yield a, X[a - row:b - row], Yb
            row += m
            if row >= hi:
                return


# ---------------------------------------------------------------------------
# per-shard partial computation
# ---------------------------------------------------------------------------


class _Folder:
    """Carried-accumulator fold of one shard's rows into a fresh
    partial sketch, at absolute row positions — the
    :mod:`libskylark_tpu.sessions.state` fold math starting from zeros
    at ``lo`` instead of a live session's cursor. Deterministic eager
    ops on host-coerced bytes: the replay/re-execution invariant.

    Twin of ``sessions.state.SessionState.fold`` (which caches the
    O(n) streams for many small appends; a shard task materializes
    only its O(shard) slice). A change to either fold must land in
    both — the shared ``transform.apply`` oracles in the two test
    suites pin them to one bit pattern."""

    def __init__(self, plan: ShardPlan, lo: int):
        import jax.numpy as jnp

        self.plan = plan
        self.t = plan._transform()
        self.rows = 0
        dt = np.dtype(plan.dtype)
        self._dt = dt
        if plan.kind in ADDITIVE_KINDS:
            self.sx = jnp.zeros((plan.s_dim, plan.d), dt)
            self.sy = (jnp.zeros((plan.s_dim, plan.targets), dt)
                       if plan.targets else None)
        else:                    # ust: collect owned sampled rows
            self._idx = np.asarray(self.t.sample_indices())
            self._out: List[np.ndarray] = []
            self._rx: List[np.ndarray] = []
            self._ry: List[np.ndarray] = []

    def fold(self, off: int, X, Y=None) -> None:
        import jax.numpy as jnp

        from libskylark_tpu.base import randgen

        p = self.plan
        X = np.asarray(X, dtype=self._dt)
        m = X.shape[0]
        if X.ndim != 2 or X.shape[1] != p.d:
            raise errors.InvalidParametersError(
                f"shard batch must be (m, {p.d}), got {X.shape}")
        if p.targets:
            if Y is None:
                raise errors.InvalidParametersError(
                    f"plan carries {p.targets} target column(s); the "
                    "source yielded none")
            Y = np.asarray(Y, dtype=self._dt).reshape(m, -1)
            if Y.shape[1] != p.targets:
                raise errors.InvalidParametersError(
                    f"Y batch must be ({m}, {p.targets}), got {Y.shape}")
        lo, hi = off, off + m
        if p.kind == "cwt":
            # positional bucket/sign slice for exactly these rows +
            # row-order scatter into the carried accumulator (the
            # io/streaming invariant: bits independent of batching)
            h = randgen.stream_slice(
                self.t.subkey(0), randgen.UniformInt(0, p.s_dim - 1),
                lo, hi, dtype=jnp.int32)
            v = randgen.stream_slice(
                self.t.subkey(1), randgen.Rademacher(), lo, hi,
                dtype=jnp.dtype(self._dt))
            Xj = jnp.asarray(X)
            self.sx = self.sx.at[h].add(v[:, None] * Xj)
            if p.targets:
                self.sy = self.sy.at[h].add(v[:, None] * jnp.asarray(Y))
        elif p.kind == "jlt":
            panel = self.t.s_panel(lo, hi, jnp.dtype(self._dt))
            self.sx = self.sx + panel @ jnp.asarray(X)
            if p.targets:
                self.sy = self.sy + panel @ jnp.asarray(Y)
        elif p.kind == "srht":
            # panel-free FWHT fold over exactly these rows (the r21
            # fix): O(rows·log rows·m) aligned-block transforms instead
            # of jnp.asarray-ing a fresh O(rows·s) operator panel on
            # every (re-)execution. operator_panel stays as the
            # bit-equality oracle (tests/test_fwht.py).
            self.sx = self.sx + self.t.fold_rows(X, lo, hi, self._dt)
            if p.targets:
                self.sy = self.sy + self.t.fold_rows(Y, lo, hi, self._dt)
        else:                    # ust
            sel = np.nonzero((self._idx >= lo) & (self._idx < hi))[0]
            if sel.size:
                self._out.append(sel.astype(np.int64))
                self._rx.append(X[self._idx[sel] - lo])
                if p.targets:
                    self._ry.append(Y[self._idx[sel] - lo])
        self.rows += m

    def partial(self) -> Dict[str, np.ndarray]:
        p = self.plan
        if p.kind in ADDITIVE_KINDS:
            out = {"SX": np.asarray(self.sx)}
            if p.targets:
                out["SY"] = np.asarray(self.sy)
            return out
        cat = (lambda lst, w: np.concatenate(lst) if lst
               else np.zeros((0, w), self._dt))
        out = {"out_idx": (np.concatenate(self._out) if self._out
                           else np.zeros(0, np.int64)),
               "rows_x": cat(self._rx, p.d)}
        if p.targets:
            out["rows_y"] = cat(self._ry, p.targets)
        return out


def compute_shard(plan: ShardPlan, index: int, source: ShardSource,
                  retry: Optional[RetryPolicy] = None
                  ) -> Dict[str, np.ndarray]:
    """Execute shard task ``index``: ingest rows ``[lo, hi)`` from
    ``source`` and fold them into a fresh partial sketch.

    The ``dist.shard`` fault site fires at entry (a ``crash`` spec here
    is the deterministic kill -9). Ingest failures matching the retry
    policy's transient predicate re-enter the source at the **consumed
    batch offset** — the carried accumulator keeps everything already
    folded, so a reconnect resumes instead of recomputing (the r9
    WebHDFS discipline promoted to the shard task)."""
    plan.validate()
    faults.check("dist.shard", detail=f"shard{index}")
    lo, hi = plan.shard_range(index)
    retry = retry or _ingest_retry()
    folder = _Folder(plan, lo)
    consumed = lo
    delays = retry.delays()
    failures = 0
    while consumed < hi:
        try:
            for off, X, Y in source.read(consumed, hi):
                faults.check("dist.ingest",
                             detail=f"shard{index}@{off}")
                folder.fold(off, X, Y)
                consumed = off + len(X)
            if consumed < hi:
                # the stream ended early: a shrunken/truncated source
                # must not fabricate missing rows — surface it (a
                # reconnect may still see the full stream, so the
                # retry ladder gets its shot before this propagates)
                raise errors.IOError_(
                    f"shard {index}: source ended at row {consumed} "
                    f"before the shard bound {hi}")
        except BaseException as e:  # noqa: BLE001 — predicate decides
            failures += 1
            if not retry.retryable(e) or failures >= retry.max_attempts:
                if isinstance(e, errors.SkylarkError):
                    e.append_trace(
                        f"dist ingest: shard {index} failed at row "
                        f"{consumed} (attempt {failures})")
                raise
            retry.sleep(next(delays))
    if folder.rows != hi - lo:
        raise errors.IOError_(
            f"shard {index} expected {hi - lo} rows, source yielded "
            f"{folder.rows}")
    return folder.partial()


def source_to_wire(source: ShardSource):
    """The cross-replica wire form of a (usually pre-sliced) source.

    Plain in-memory :class:`ArraySource` instances flatten to a dict
    whose row arrays sit directly in a top-level container — within the
    shm transport's scan depth (:mod:`libskylark_tpu.fleet.shm`
    recurses containers two levels), so a shard task dispatched to a
    process replica ships its rows as zero-copy ring segments instead
    of pickled bytes down the pipe. Everything else (range-readable
    descriptors, test/source subclasses with overridden ``read``)
    passes through unchanged and pickles as before."""
    if type(source) is ArraySource:
        wire = {"__kind__": "array_source", "offset": source._off,
                "batch_rows": source.batch_rows, "X": source._X}
        if source._Y is not None:
            wire["Y"] = source._Y
        return wire
    return source


def source_from_wire(obj) -> ShardSource:
    """Inverse of :func:`source_to_wire` (identity for pass-throughs).
    Decoded shm views arrive read-only; ``ArraySource`` never writes
    its rows, so the view is used as-is — the zero-copy half of the
    contract."""
    if isinstance(obj, dict) and obj.get("__kind__") == "array_source":
        return ArraySource(obj["X"], obj.get("Y"),
                           batch_rows=int(obj["batch_rows"]),
                           offset=int(obj["offset"]))
    return obj


def execute_task(payload: Mapping) -> dict:
    """The replica-side entry point of one shard task (the ``shard``
    verb of :class:`libskylark_tpu.fleet.Replica` lands here). The
    payload carries the serialized plan, the shard index, and the
    range-readable source (possibly pre-sliced to just this shard's
    rows, possibly in :func:`source_to_wire` form)."""
    plan = ShardPlan.from_dict(payload["plan"])
    index = int(payload["index"])
    lo, hi = plan.shard_range(index)
    source = source_from_wire(payload["source"])
    return {"index": index, "rows": hi - lo,
            "partial": compute_shard(plan, index, source)}


# ---------------------------------------------------------------------------
# merge: canonical deterministic tree + coverage accounting
# ---------------------------------------------------------------------------


def merge_partials(plan: ShardPlan, partials: Mapping[int, Mapping]
                   ) -> Dict[str, np.ndarray]:
    """Merge per-shard partials into one sketch.

    Additive kinds canonicalize to ascending shard index and reduce
    through a fixed pairwise tree — the merged bits depend only on the
    *set* of present shards, never on arrival order or intermediate
    grouping (the merge-order-invariance property the test battery
    pins). ``ust`` partials place their owned output rows (exact —
    no floating-point combination). ``dist.merge`` is the chaos seam."""
    import jax.numpy as jnp

    plan.validate()
    faults.check("dist.merge",
                 detail=f"{plan.kind}:{len(partials)} partials")
    order = sorted(int(i) for i in partials)
    if plan.kind not in ADDITIVE_KINDS:
        dt = np.dtype(plan.dtype)
        sx = np.zeros((plan.s_dim, plan.d), dt)
        sy = (np.zeros((plan.s_dim, plan.targets), dt)
              if plan.targets else None)
        for i in order:
            p = partials[i]
            idx = np.asarray(p["out_idx"], np.int64)
            sx[idx] = np.asarray(p["rows_x"], dt)
            if sy is not None:
                sy[idx] = np.asarray(p["rows_y"], dt)
        out = {"SX": sx}
        if sy is not None:
            out["SY"] = sy
        return out

    def tree(arrs):
        # fixed pairwise reduction over the canonical order: log-depth
        # and deterministic for a given present-set
        while len(arrs) > 1:
            nxt = [arrs[k] + arrs[k + 1] if k + 1 < len(arrs)
                   else arrs[k]
                   for k in range(0, len(arrs), 2)]
            arrs = nxt
        return arrs[0]

    dt = np.dtype(plan.dtype)
    if not order:
        out = {"SX": np.zeros((plan.s_dim, plan.d), dt)}
        if plan.targets:
            out["SY"] = np.zeros((plan.s_dim, plan.targets), dt)
        return out
    out = {"SX": np.asarray(tree(
        [jnp.asarray(np.asarray(partials[i]["SX"], dt)) for i in order]))}
    if plan.targets:
        out["SY"] = np.asarray(tree(
            [jnp.asarray(np.asarray(partials[i]["SY"], dt))
             for i in order]))
    return out


def missing_ranges(plan: ShardPlan, merged: Iterator[int]
                   ) -> Tuple[Tuple[int, int], ...]:
    """Coalesced global row ranges of the shards NOT in ``merged``."""
    present = set(int(i) for i in merged)
    out: List[List[int]] = []
    for i, lo, hi in plan.shards():
        if i in present:
            continue
        if out and out[-1][1] == lo:
            out[-1][1] = hi
        else:
            out.append([lo, hi])
    return tuple((a, b) for a, b in out)


# ---------------------------------------------------------------------------
# results: coverage is part of the answer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DistSketchResult:
    """A merged distributed sketch plus its exact coverage accounting.
    ``coverage`` is the fraction of the plan's ``n`` rows folded into
    the merge (``1.0`` = every shard present); ``missing`` the
    coalesced global row ranges of abandoned shards. ``SY`` is ``None``
    when the plan carries no targets."""

    kind: str
    SX: np.ndarray
    SY: Optional[np.ndarray]
    rows_merged: int
    coverage: float
    missing: Tuple[Tuple[int, int], ...]
    shards: int
    shards_merged: int

    @property
    def degraded(self) -> bool:
        return self.coverage < 1.0

    def require(self, min_coverage: float) -> "DistSketchResult":
        """Gate: raise :class:`~libskylark_tpu.base.errors.
        SketchCoverageError` when the merge covered less than
        ``min_coverage`` of the declared rows — the never-silently-
        partial contract."""
        if self.coverage < float(min_coverage):
            raise errors.SketchCoverageError(
                f"distributed sketch covered {self.coverage:.6f} of the "
                f"rows (< min_coverage={min_coverage}); missing row "
                f"ranges: {list(self.missing)}",
                coverage=self.coverage, missing=self.missing)
        return self


class DegradedSketchResult(DistSketchResult):
    """A merge that lost at least one shard for good: a valid sketch of
    the surviving rows, with the loss quantified (``coverage`` < 1 and
    the exact ``missing`` ranges). Returned only when the caller's
    ``min_coverage`` admits it; below the gate the coordinator raises
    instead."""


def build_result(plan: ShardPlan, partials: Mapping[int, Mapping]
                 ) -> DistSketchResult:
    """Merge + exact coverage accounting in one step."""
    merged = merge_partials(plan, partials)
    rows = sum(hi - lo for i, lo, hi in plan.shards() if i in partials)
    missing = missing_ranges(plan, partials.keys())
    cls = DistSketchResult if rows == plan.n else DegradedSketchResult
    return cls(kind=plan.kind, SX=merged["SX"], SY=merged.get("SY"),
               rows_merged=rows, coverage=rows / plan.n,
               missing=missing, shards=plan.num_shards,
               shards_merged=len(partials))


def sketch_local(plan: ShardPlan, source: ShardSource,
                 retry: Optional[RetryPolicy] = None) -> DistSketchResult:
    """The one-shot reference: every shard computed sequentially in
    this process, merged through the same canonical tree. A
    full-coverage distributed run — whatever crashed, retried, or got
    reassigned along the way — is **bit-equal** to this by
    construction, which is what the chaos/CI gates pin."""
    partials = {i: compute_shard(plan, i, source, retry=retry)
                for i, _, _ in plan.shards()}
    return build_result(plan, partials)


__all__ = [
    "ADDITIVE_KINDS", "ArraySource", "DegradedSketchResult",
    "DistSketchResult", "HDF5Source", "KINDS", "LibsvmSource",
    "ShardPlan", "ShardSource", "build_result", "compute_shard",
    "execute_task", "merge_partials", "missing_ranges", "sketch_local",
    "source_from_wire", "source_to_wire",
]
