"""Pipelined dist-serve jobs: shard fan-out as a first-class serve path.

The r17 :class:`~libskylark_tpu.dist.coordinator.DistSketchCoordinator`
is a one-shot library API with a barrier at the end: every shard
settles, *then* the merge runs. This module is the serve-tier promotion
(ROADMAP item 1): ``submit_dist_sketch`` / ``submit_dist_lstsq`` /
``submit_dist_svd`` on :class:`~libskylark_tpu.engine.serve.
MicrobatchExecutor` and :class:`~libskylark_tpu.fleet.Router` drive a
:class:`DistServeJob` here, which keeps the coordinator's placement,
retry, hedge and accounting semantics but

- **merges incrementally as partials land** (:class:`IncrementalMerger`
  — the canonical pairwise tree evaluated eagerly, node by node, the
  moment both children exist), so ingest, shard compute and merging
  overlap instead of barriering; wall-clock is set by the slowest
  *stage*, not the sum of stages;
- **bills retries and hedges to the owning tenant's token bucket**
  (docs/qos): the original admission covers every first attempt; each
  re-execution or straggler mirror charges one more token, and quota
  exhaustion stops further attempts (the shard degrades into the
  abandoned accounting) rather than crashing the job;
- **honors per-class ``min_coverage`` SLOs**: interactive-class jobs
  may resolve EARLY with a quantified
  :class:`~libskylark_tpu.dist.plan.DegradedSketchResult` once coverage
  reaches the gate and every unresolved shard has already failed at
  least once; standard/best_effort jobs run the storm to completion and
  gate the final merge (``SKYLARK_DIST_SERVE_MIN_COVERAGE_*``);
- **span-parents every shard task** under the originating
  ``serve.submit`` request id (``dist.shard_task`` spans), and
  disaggregates dispatch by replica (``dist.shard_tasks``).

Determinism: the merged bits are unchanged from the coordinator path.
A full-coverage job returns bits equal to
:func:`~libskylark_tpu.dist.plan.sketch_local` — the eager tree
combines exactly the pairs, in exactly the association order, of
:func:`~libskylark_tpu.dist.plan.merge_partials` over the full shard
set. A degraded additive merge falls back to the canonical one-shot
merge over the surviving partials (sketch-sized, cheap — the overlap
the pipeline buys is in the common full-coverage path); ``ust``
placement is exact at any coverage. ``SKYLARK_DIST_SERVE_MERGE_FANIN``
is a scheduling knob only and never changes bits.

Cross-replica traffic stays proportional to sketch size: in-memory
sources ship one shard's rows (zero-copy over the fleet's shm rings for
process replicas — :func:`~libskylark_tpu.dist.plan.source_to_wire`),
range-readable sources (HDF5 / libsvm / webhdfs) ship only their
descriptor, and every reply is one ``s_dim x d`` partial.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, wait
from typing import Dict, List, Optional

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.dist import plan as _plan
from libskylark_tpu.dist.coordinator import (_COVERAGE, _MERGES,
                                             DistSketchCoordinator, _life,
                                             _retryable)
from libskylark_tpu.engine import resultcache as _rcache
from libskylark_tpu.qos import tenants as _qtenants
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience.policy import Deadline
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# Unified-registry instruments (docs/observability): declared in
# telemetry/names.py, created here once. ``dist.shard_tasks``
# disaggregates by replica so shard placement skew is visible on the
# Prometheus surface; ``dist.coverage`` / ``dist.merges`` stay owned by
# the coordinator module (one creation site per name) and are updated
# from here through the imported instruments.
_SHARD_TASKS = _metrics.counter(
    "dist.shard_tasks",
    "Dist-serve shard-task dispatches, disaggregated by replica")
_MERGE_DEPTH = _metrics.gauge(
    "dist.merge_depth",
    "Tree depth of the most recent incremental dist-serve merge")
_JOBS = _metrics.counter(
    "dist.jobs", "Dist-serve jobs started (all endpoints)")
_EARLY = _metrics.counter(
    "dist.early_resolves",
    "Interactive dist-serve jobs resolved early at their coverage gate")

_SS_LOCK = _locks.make_lock("dist.serve.lifetime")
_SS = {"jobs": 0, "shard_tasks": 0, "early_resolves": 0,
       "retries_billed": 0, "hedges_billed": 0, "quota_stopped": 0,
       "merge_depth_peak": 0, "last_coverage": None,
       "by_replica": {}}


def _ss(**deltas) -> None:
    with _SS_LOCK:
        for k, v in deltas.items():
            if k == "last_coverage":
                _SS[k] = v
            elif k == "merge_depth_peak":
                _SS[k] = max(_SS[k], v)
            elif k == "by_replica":
                by = _SS["by_replica"]
                for name, n in v.items():
                    by[name] = by.get(name, 0) + n
            else:
                _SS[k] += v


def dist_serve_stats() -> dict:
    """Process-lifetime dist-serve rollup (the ``dist_serve`` telemetry
    collector): jobs, shard-task dispatch (with ``by_replica``
    disaggregation), early resolves, retry/hedge billing."""
    with _SS_LOCK:
        out = dict(_SS)
        out["by_replica"] = dict(_SS["by_replica"])
        return out


_metrics.register_collector("dist_serve", dist_serve_stats)


def class_min_coverage(qos_class: Optional[str]) -> float:
    """The per-class default ``min_coverage`` gate
    (``SKYLARK_DIST_SERVE_MIN_COVERAGE_*``; docs/qos). Unknown or
    custom class names gate at 1.0 — relaxed coverage is always an
    explicit opt-in."""
    cls = _qtenants.coerce_class(qos_class)
    var = {
        _qtenants.INTERACTIVE: _env.DIST_SERVE_MIN_COVERAGE_INTERACTIVE,
        _qtenants.STANDARD: _env.DIST_SERVE_MIN_COVERAGE_STANDARD,
        _qtenants.BEST_EFFORT: _env.DIST_SERVE_MIN_COVERAGE_BEST_EFFORT,
    }.get(cls)
    return float(var.get()) if var is not None else 1.0


# ---------------------------------------------------------------------------
# content identity: dist results are pure functions of
# (source digest, plan fingerprint, seed) — digested once, at the
# front door, so the router's single-flight and the owning executor's
# result cache share one key without re-hashing anywhere downstream
# ---------------------------------------------------------------------------


def source_digest_parts(source: _plan.ShardSource) -> list:
    """Digest parts identifying a shard source. In-memory sources are
    content-addressed (the rows ARE the identity); descriptor sources
    are addressed by descriptor — the path names the content on shared
    storage, and re-digesting terabytes through the front door would
    defeat the ship-the-sketch economics (callers who need content
    addressing for mutable files should version the path)."""
    if type(source) is _plan.ArraySource:
        parts = [("source_kind", "array"), ("X", source._X)]
        if source._Y is not None:
            parts.append(("Y", source._Y))
        parts.append(("offset", str(source._off)))
        parts.append(("batch_rows", str(source.batch_rows)))
        return parts
    fields = {"source_kind": type(source).__name__,
              "n": int(source.n), "d": int(source.d),
              "targets": int(source.targets)}
    for k in ("path", "batch_rows"):
        v = getattr(source, k, None)
        if v is not None:
            fields[k] = v
    return [("source", json.dumps(fields, sort_keys=True, default=str))]


def dist_request_digest(endpoint: str, plan: _plan.ShardPlan,
                        source: _plan.ShardSource, extra=()) -> str:
    """The content digest of one dist-serve request. The plan's
    serialized identity pins kind/dims/seed/shard grid; the source
    parts pin the data; ``extra`` carries endpoint statics (e.g. the
    SVD rank) that change the answer without changing the sketch."""
    parts = [("endpoint", str(endpoint)),
             ("plan", json.dumps(plan.to_dict(), sort_keys=True))]
    parts += source_digest_parts(source)
    parts += [(str(k), str(v)) for k, v in extra]
    return _rcache.operand_digest(parts)


# ---------------------------------------------------------------------------
# incremental merge: the canonical tree, evaluated as partials land
# ---------------------------------------------------------------------------


class IncrementalMerger:
    """Eager evaluation of :func:`~libskylark_tpu.dist.plan.
    merge_partials`' canonical pairwise tree.

    The tree over the FULL shard set has a fixed shape (leaf ``i`` at
    position ``i``; each level pairs adjacent nodes, an odd tail passes
    through), so a node can combine the moment both children exist —
    merge work overlaps the storm instead of running after it. Leaf
    conversion and the combine op mirror ``merge_partials`` exactly,
    which is what makes the full-coverage eager root bit-equal to the
    one-shot merge (and hence to ``sketch_local``).

    A *degraded* additive merge compacts to the surviving shard list
    first — a different tree shape, unknowable until abandonment — so
    :meth:`result` falls back to the canonical one-shot merge over the
    kept raw partials (sketch-sized; the rare path). ``ust`` placement
    is disjoint-exact and stays incremental at any coverage.

    ``fanin`` bounds how many ready combines fold per :meth:`add` call
    (burst control on the driver thread); leftovers drain on later
    adds or at :meth:`result`. It never changes the tree, so it never
    changes bits."""

    def __init__(self, plan: _plan.ShardPlan, fanin: Optional[int] = None):
        self.plan = plan
        self.fanin = max(1, int(fanin if fanin is not None
                                else _env.DIST_SERVE_MERGE_FANIN.get()))
        self.partials: Dict[int, dict] = {}
        self.rows = 0
        self.merge_ops = 0
        self.depth = 0
        self._additive = plan.kind in _plan.ADDITIVE_KINDS
        sizes = [plan.num_shards]
        while sizes[-1] > 1:
            sizes.append(-(-sizes[-1] // 2))
        self._sizes = sizes
        if self._additive:
            self._vals: dict = {}          # (level, pos) -> (SX, SY|None)
            self._ready: collections.deque = collections.deque()
        else:
            dt = np.dtype(plan.dtype)
            self._sx = np.zeros((plan.s_dim, plan.d), dt)
            self._sy = (np.zeros((plan.s_dim, plan.targets), dt)
                        if plan.targets else None)

    @property
    def coverage(self) -> float:
        return self.rows / self.plan.n

    def add(self, index: int, partial: dict) -> None:
        index = int(index)
        if index in self.partials:
            return                        # hedge twin: identical bits
        self.partials[index] = partial
        lo, hi = self.plan.shard_range(index)
        self.rows += hi - lo
        if not self._additive:            # ust: disjoint placement
            dt = self._sx.dtype
            idx = np.asarray(partial["out_idx"], np.int64)
            self._sx[idx] = np.asarray(partial["rows_x"], dt)
            if self._sy is not None:
                self._sy[idx] = np.asarray(partial["rows_y"], dt)
            return
        import jax.numpy as jnp

        dt = np.dtype(self.plan.dtype)
        sx = jnp.asarray(np.asarray(partial["SX"], dt))
        sy = (jnp.asarray(np.asarray(partial["SY"], dt))
              if self.plan.targets else None)
        self._vals[(0, index)] = (sx, sy)
        self._note_ready(0, index)
        self._drain(self.fanin)

    def _note_ready(self, level: int, pos: int) -> None:
        # climb pass-through tails eagerly (an unpaired node at the end
        # of an odd-length level IS its parent in the canonical tree);
        # queue a real combine once the sibling exists
        while level + 1 < len(self._sizes):
            if pos % 2 == 0 and pos + 1 >= self._sizes[level]:
                self._vals[(level + 1, pos // 2)] = \
                    self._vals.pop((level, pos))
                level, pos = level + 1, pos // 2
                self.depth = max(self.depth, level)
                continue
            if (level, pos ^ 1) in self._vals:
                self._ready.append((level + 1, pos // 2))
            return

    def _drain(self, budget: Optional[int]) -> None:
        while self._ready and (budget is None or budget > 0):
            level, pos = self._ready.popleft()
            left = self._vals.pop((level - 1, 2 * pos), None)
            right = self._vals.pop((level - 1, 2 * pos + 1), None)
            if left is None or right is None:   # already folded upward
                continue
            sx = left[0] + right[0]
            sy = (left[1] + right[1] if left[1] is not None else None)
            self._vals[(level, pos)] = (sx, sy)
            self.merge_ops += 1
            self.depth = max(self.depth, level)
            if budget is not None:
                budget -= 1
            self._note_ready(level, pos)

    @staticmethod
    def _frozen(a) -> np.ndarray:
        out = np.asarray(a)
        if out.flags.writeable:
            try:
                out.setflags(write=False)
            except ValueError:
                out = np.array(out)
                out.setflags(write=False)
        return out

    def result(self) -> _plan.DistSketchResult:
        """The merged result over every partial added so far, with the
        exact coverage accounting of :func:`~libskylark_tpu.dist.plan.
        build_result`. Arrays come back read-only — the dist result is
        shareable through the result cache without a defensive copy."""
        plan = self.plan
        full = len(self.partials) == plan.num_shards
        if self._additive and not full:
            # canonical fallback (fires the dist.merge chaos seam
            # itself): the compacted-survivor tree shape only exists
            # now that the present set is final
            merged = _plan.merge_partials(plan, self.partials)
        else:
            faults.check(
                "dist.merge",
                detail=f"{plan.kind}:{len(self.partials)} partials")
            if self._additive:
                self._drain(None)
                root = self._vals[(len(self._sizes) - 1, 0)]
                merged = {"SX": np.asarray(root[0])}
                if plan.targets:
                    merged["SY"] = np.asarray(root[1])
            else:
                merged = {"SX": self._sx}
                if self._sy is not None:
                    merged["SY"] = self._sy
        missing = _plan.missing_ranges(plan, self.partials.keys())
        cls = (_plan.DistSketchResult if self.rows == plan.n
               else _plan.DegradedSketchResult)
        sy = merged.get("SY")
        return cls(kind=plan.kind, SX=self._frozen(merged["SX"]),
                   SY=self._frozen(sy) if sy is not None else None,
                   rows_merged=self.rows, coverage=self.rows / plan.n,
                   missing=missing, shards=plan.num_shards,
                   shards_merged=len(self.partials))


# ---------------------------------------------------------------------------
# the pipelined job
# ---------------------------------------------------------------------------


class _JobAttempt:
    __slots__ = ("index", "future", "replica", "attempt", "t0", "hedge",
                 "span_cm", "span")

    def __init__(self, index, future, replica, attempt, hedge=False):
        self.index = index
        self.future = future
        self.replica = replica
        self.attempt = attempt
        self.t0 = time.monotonic()
        self.hedge = hedge
        self.span_cm = None
        self.span = None


class DistServeJob:
    """One pipelined dist-serve job: the coordinator's storm loop with
    incremental merging, per-class coverage gates, early resolve and
    tenant-billed retries/hedges (module doc). Placement, failover
    order, retry budget and hedging all come from ``coordinator``
    (shared across jobs — its accounting aggregates the fleet's
    shard traffic); a coordinator with no fleet computes shards on a
    private thread pool, so ingest/compute/merge still overlap on a
    single host.

    Run :meth:`run` on a worker thread (the executor/router endpoints
    do) — it blocks until the job resolves."""

    def __init__(self, plan: _plan.ShardPlan, source: _plan.ShardSource,
                 *, coordinator: Optional[DistSketchCoordinator] = None,
                 qos_class: Optional[str] = None, tenant: str = "",
                 registry=None, min_coverage: Optional[float] = None,
                 deadline=None, pipeline: Optional[int] = None,
                 fanin: Optional[int] = None,
                 request_id: Optional[str] = None, parent_ctx=None):
        plan.validate()
        if source.n < plan.n:
            raise errors.InvalidParametersError(
                f"source holds {source.n} rows < plan.n={plan.n}")
        self.plan = plan
        self.source = source
        self.co = coordinator if coordinator is not None \
            else DistSketchCoordinator()
        self.qos_class = _qtenants.coerce_class(qos_class)
        self.tenant = str(tenant) if tenant else ""
        self.registry = registry
        self.gate = (class_min_coverage(self.qos_class)
                     if min_coverage is None else float(min_coverage))
        self.deadline = Deadline.coerce(deadline)
        depth = int(pipeline if pipeline is not None
                    else _env.DIST_SERVE_PIPELINE.get())
        self.cap = depth if depth > 0 else (
            self.co._max_inflight
            or max(2, 2 * max(1, len(self.co._names()))))
        self.fanin = fanin
        self.rid = request_id
        self.parent = parent_ctx
        # interactive is the only class whose latency SLO buys early
        # resolution; a gate of 1.0 makes "early" meaningless anyway
        self._early_ok = (self.qos_class == _qtenants.INTERACTIVE
                          and self.gate < 1.0)
        self._tpe = None
        self.stats = {"shard_tasks": 0, "retries_billed": 0,
                      "hedges_billed": 0, "quota_stopped": 0,
                      "early_resolved": False, "merge_depth": 0,
                      "merge_ops": 0, "coverage": None,
                      "by_replica": {}}

    # -- billing --------------------------------------------------------

    def _bill(self, what: str) -> bool:
        """Charge one token for a retry/hedge attempt. ``True`` =
        proceed; ``False`` = the tenant's bucket is empty — the extra
        attempt is refused (never raises: quota exhaustion degrades
        the job, it does not crash it)."""
        if self.registry is None or not self.tenant:
            return True
        try:
            self.registry.admit(self.tenant)
        except errors.TenantQuotaError:
            self.stats["quota_stopped"] += 1
            _ss(quota_stopped=1)
            return False
        key = "retries_billed" if what == "retry" else "hedges_billed"
        self.stats[key] += 1
        _ss(**{key: 1})
        return True

    # -- span plumbing --------------------------------------------------

    def _open_span(self, att: _JobAttempt) -> None:
        if self.rid is None and self.parent is None:
            return
        cm = _trace.span(
            "dist.shard_task",
            attrs={"index": att.index, "replica": att.replica,
                   "attempt": att.attempt, "hedge": att.hedge},
            parent=self.parent, request_id=self.rid)
        att.span_cm = cm
        try:
            att.span = cm.__enter__()
        except Exception:      # noqa: BLE001 — tracing must not kill jobs
            att.span_cm = None

    def _close_span(self, att: _JobAttempt, outcome: str,
                    error=None) -> None:
        if att.span_cm is None:
            return
        if att.span is not None:
            att.span.set_attr("outcome", outcome)
            if error is not None:
                att.span.set_attr("error", repr(error))
        try:
            att.span_cm.__exit__(None, None, None)
        except Exception:      # noqa: BLE001
            pass
        att.span_cm = None

    # -- the pipelined storm --------------------------------------------

    def run(self) -> _plan.DistSketchResult:
        plan, source, co = self.plan, self.source, self.co
        merger = IncrementalMerger(plan, self.fanin)
        pending = [i for i, _, _ in plan.shards()]
        tried: Dict[int, List[str]] = {i: [] for i in pending}
        attempts: Dict[int, int] = {i: 0 for i in pending}
        last_ran: Dict[int, str] = {}
        inflight: Dict[Future, _JobAttempt] = {}
        abandoned: List[int] = []
        hedged: set = set()
        plan_doc = plan.to_dict()
        fingerprint = plan.fingerprint()
        deadline = self.deadline
        _JOBS.inc()
        _ss(jobs=1)

        def task_payload(index: int) -> dict:
            lo, hi = plan.shard_range(index)
            return {"plan": plan_doc, "index": index,
                    "source": _plan.source_to_wire(
                        source.subrange(lo, hi))}

        def record(index: int, fut, name: str, hedge: bool) -> None:
            prev = last_ran.get(index)
            last_ran[index] = name
            if name not in tried[index]:
                tried[index].append(name)
            att = _JobAttempt(index, fut, name, attempts[index],
                              hedge=hedge)
            self._open_span(att)
            inflight[fut] = att
            co._account("dispatched", name)
            _SHARD_TASKS.inc(replica=name)
            _ss(shard_tasks=1, by_replica={name: 1})
            self.stats["shard_tasks"] += 1
            by = self.stats["by_replica"]
            by[name] = by.get(name, 0) + 1
            if not hedge and attempts[index] > 0:
                co._account("retried", name)
                if prev is not None and prev != name:
                    co._account("reassigned", name)

        def dispatch(index: int, *, hedge: bool = False,
                     exclude: Optional[str] = None) -> bool:
            with co._lock:        # jobs share the coordinator's ring
                cands = co._candidates(fingerprint, index,
                                       avoid=tried[index])
            if exclude is not None:
                cands = [n for n in cands if n != exclude]
            for name in cands:
                try:
                    fut = co._get(name).shard(task_payload(index))
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — a refusal
                    if not _retryable(e):
                        raise
                    if name not in tried[index]:
                        tried[index].append(name)
                    continue
                record(index, fut, name, hedge)
                return True
            if not cands and co._pool is None and co._replicas is None:
                # no fleet: shard compute runs on the job's own pool —
                # pipelined even on one host (ingest overlaps folds)
                if self._tpe is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._tpe = ThreadPoolExecutor(
                        max_workers=max(1, min(self.cap, 8)),
                        thread_name_prefix="skylark-dist-serve")
                fut = self._tpe.submit(_plan.execute_task,
                                       task_payload(index))
                record(index, fut, "<local>", hedge)
                return True
            return False

        def note_failure(index: int, exc: Optional[BaseException],
                         bill: bool = True) -> None:
            if exc is not None and not _retryable(exc):
                raise exc
            attempts[index] += 1
            if attempts[index] > co.retries or (
                    bill and not self._bill("retry")):
                if index not in abandoned:
                    abandoned.append(index)
                    co._account("abandoned", None)
            else:
                hedged.discard(index)
                pending.append(index)

        refusal_streak = 0
        try:
            while pending or inflight:
                if deadline is not None and deadline.expired:
                    for fut, att in list(inflight.items()):
                        self._close_span(att, "deadline")
                        if att.index not in merger.partials \
                                and att.index not in abandoned:
                            abandoned.append(att.index)
                            co._account("abandoned", None)
                    inflight.clear()
                    for index in pending:
                        if index not in abandoned:
                            abandoned.append(index)
                            co._account("abandoned", None)
                    pending = []
                    break
                while pending and len(inflight) < self.cap:
                    index = pending.pop(0)
                    if index in merger.partials or index in abandoned:
                        continue
                    if dispatch(index):
                        refusal_streak = 0
                    else:
                        # a refusal burns budget but bills nothing —
                        # no replica executed anything
                        note_failure(index, None, bill=False)
                        refusal_streak += 1
                        break
                if not inflight:
                    if pending:
                        if refusal_streak:
                            delay = min(0.05 * refusal_streak, 1.0)
                            if deadline is not None:
                                delay = min(delay, max(
                                    deadline.remaining(), 0.0))
                            time.sleep(delay)
                        continue
                    break
                poll = (0.05 if co.hedge or deadline is not None
                        else None)
                done, _ = wait(list(inflight), timeout=poll,
                               return_when=FIRST_COMPLETED)
                now = time.monotonic()
                if co.hedge and not done:
                    for fut, att in list(inflight.items()):
                        if len(inflight) >= self.cap:
                            break
                        if (not att.hedge and att.index not in hedged
                                and now - att.t0 >= co.hedge_delay_s):
                            # mirrors are extra capacity: billed before
                            # launch, and an empty bucket simply skips
                            # this tick (the shard stays eligible)
                            if not self._bill("hedge"):
                                continue
                            if dispatch(att.index, hedge=True,
                                        exclude=att.replica):
                                hedged.add(att.index)
                                co._account("hedged", None)
                for fut in done:
                    att = inflight.pop(fut, None)
                    if att is None:
                        continue
                    if att.index in merger.partials \
                            or att.index in abandoned:
                        self._close_span(att, "dropped")
                        continue
                    exc = fut.exception()
                    if exc is None:
                        self._close_span(att, "settled")
                        merger.add(att.index, fut.result()["partial"])
                        for f2 in [f for f, a in inflight.items()
                                   if a.index == att.index]:
                            self._close_span(inflight.pop(f2), "dropped")
                    else:
                        self._close_span(att, "failed", error=exc)
                        twins = [a for a in inflight.values()
                                 if a.index == att.index]
                        if not twins:
                            note_failure(att.index, exc)
                if self._early_ok and merger.rows >= self.gate * plan.n:
                    unsettled = [i for i in attempts
                                 if i not in merger.partials
                                 and i not in abandoned]
                    if unsettled and all(attempts[i] >= 1
                                         for i in unsettled):
                        # coverage met, every holdout already failed
                        # once: resolve now — the missing ranges ride
                        # the DegradedSketchResult, quantified
                        for i in unsettled:
                            abandoned.append(i)
                            co._account("abandoned", None)
                        for f2, a2 in list(inflight.items()):
                            self._close_span(a2, "early_resolve")
                        inflight.clear()
                        pending = []
                        self.stats["early_resolved"] = True
                        _EARLY.inc()
                        _ss(early_resolves=1)
                        break
        finally:
            for att in inflight.values():
                self._close_span(att, "aborted")
            if self._tpe is not None:
                self._tpe.shutdown(wait=False)
        result = merger.result()
        _MERGES.inc()
        _COVERAGE.set(result.coverage)
        _life(merges=1, last_coverage=result.coverage)
        with co._lock:
            co._stats["merges"] += 1
            co._stats["last_coverage"] = result.coverage
        _MERGE_DEPTH.set(merger.depth)
        _ss(merge_depth_peak=merger.depth,
            last_coverage=result.coverage)
        self.stats["merge_depth"] = merger.depth
        self.stats["merge_ops"] = merger.merge_ops
        self.stats["coverage"] = result.coverage
        return result.require(self.gate)


def run_job_into(job: DistServeJob, fut: Future, *, solve=None,
                 on_done=None) -> threading.Thread:
    """Run ``job`` on a daemon thread, resolving ``fut`` with its
    result (through ``solve`` when given — the local lstsq/SVD factor
    step of the dist algorithms). ``on_done(job, fut)`` runs after the
    future settles, before any caller-visible callback fires."""
    def _run():
        try:
            res = job.run()
            value = solve(res) if solve is not None else res
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — resolve, don't leak
            if on_done is not None:
                try:
                    on_done(job, e)
                except Exception:  # noqa: BLE001
                    pass
            fut.set_exception(e)
            return
        if on_done is not None:
            try:
                on_done(job, None)
            except Exception:  # noqa: BLE001
                pass
        fut.set_result(value)

    t = threading.Thread(target=_run, name="skylark-dist-serve-job",
                         daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# local factor steps (the sketch-size-communication algorithms of
# dist/algorithms.py, reused verbatim by the serve endpoints)
# ---------------------------------------------------------------------------


def solve_lstsq(result: _plan.DistSketchResult) -> dict:
    """``min_w ||X w - Y||`` from the merged joint sketch (the
    ``sketched_lstsq`` factor step)."""
    import jax.numpy as jnp

    w, *_ = jnp.linalg.lstsq(jnp.asarray(result.SX),
                             jnp.asarray(result.SY))
    return {"coef": np.asarray(w), "coverage": result.coverage,
            "missing": list(result.missing),
            "degraded": result.degraded}


def solve_svd(result: _plan.DistSketchResult, rank: int) -> dict:
    """Top-``rank`` factorization of the merged row sketch (the
    ``randomized_svd`` factor step)."""
    import jax.numpy as jnp

    _, sv, Vt = jnp.linalg.svd(jnp.asarray(result.SX),
                               full_matrices=False)
    k = min(int(rank), int(result.SX.shape[0]), int(result.SX.shape[1]))
    return {"singular_values": np.asarray(sv[:k]),
            "Vt": np.asarray(Vt[:k]), "coverage": result.coverage,
            "missing": list(result.missing),
            "degraded": result.degraded}


__all__ = [
    "DistServeJob", "IncrementalMerger", "class_min_coverage",
    "dist_request_digest", "dist_serve_stats", "run_job_into",
    "solve_lstsq", "solve_svd", "source_digest_parts",
]
