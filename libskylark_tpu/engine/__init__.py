"""Solver-pipeline compilation engine: compile once, serve many.

The NLA/ML layers' headline algorithms (randomized SVD, sketch-
preconditioned least squares, random-features KRR) are whole-solver
``jax.jit`` programs served from a donation-aware executable cache —
the layer above the sketch-apply autotuner (:mod:`libskylark_tpu.tune`):
tune certifies *kernel plans*, the engine caches the *compiled solver
executables* whose keys include the plan fingerprint, so a certified
plan change recompiles exactly the affected pipelines.

Public surface::

    compiled(fn, static_argnames=..., donate_argnums=..., key_fn=...)
    stats() / reset()          # hit/miss/recompile/compile-time counters
    cache()                    # the LRU itself (snapshot, keys)
    donation_enabled() / maybe_donate(argnums)
    enable_persistent_cache()  # jax.experimental.compilation_cache wiring
    dump_stats(path)           # the CI jit-leak gate's exit artifact
    MicrobatchExecutor(...)    # shape-bucketed microbatch serving
    serve_stats()              # aggregate serving counters (docs/serving)
    SERVING/DEGRADED/DRAINING/STOPPED   # executor health states; the
                               # poison-isolation + drain story is
                               # docs/resilience (r9)

Environment: ``SKYLARK_EXEC_CACHE_SIZE`` (LRU capacity, default 128),
``SKYLARK_AOT_DIR`` (persistent AOT executable-artifact store —
load-instead-of-compile plus cross-process single-flight; see
:mod:`libskylark_tpu.engine.aot` and docs/performance),
``SKYLARK_EXEC_CACHE_DIR`` (jax persistent compilation cache; also a
deprecated alias for the artifact store at ``<dir>/aot``),
``SKYLARK_ENGINE_DONATE=1`` (solver entry points donate operands),
``SKYLARK_ENGINE_STATS_DUMP`` (write counters JSON at process exit).
"""

from libskylark_tpu.engine import aot, bucket, warmup
from libskylark_tpu.engine.cache import (CacheEntry, EngineStats,
                                         ExecutableCache)
from libskylark_tpu.engine.compiled import (CompiledFn, cache, code_version,
                                            compiled, digest,
                                            donation_enabled, dump_stats,
                                            enable_persistent_cache,
                                            maybe_donate, plan_fingerprint,
                                            reset, stats)
from libskylark_tpu.engine.serve import (DEGRADED, DRAINING, SERVING,
                                         STOPPED, MicrobatchExecutor,
                                         ServeOverloadedError,
                                         request_statics, serve_stats)

__all__ = [
    "CacheEntry", "CompiledFn", "DEGRADED", "DRAINING", "EngineStats",
    "ExecutableCache", "MicrobatchExecutor", "SERVING", "STOPPED",
    "ServeOverloadedError", "aot", "bucket", "cache",
    "code_version", "compiled", "digest", "donation_enabled", "dump_stats",
    "enable_persistent_cache", "maybe_donate", "plan_fingerprint",
    "request_statics", "reset", "serve_stats", "stats", "warmup",
]
