"""``engine.aot`` — persistent AOT executable artifacts.

The r7 executable cache made every solver/serve program compile once
*per process*; this layer makes it compile once *per fleet*. Every AOT
compile that goes through :mod:`libskylark_tpu.engine.compiled` is
serialized (``jax.experimental.serialize_executable``) into an artifact
store under ``SKYLARK_AOT_DIR``, addressed by a digest of the exact
executable-cache key — (solver name, code-version hash, statics,
key_fn extras incl. the serve kernel ``plan_id``, avals, sharding,
donation, plan fingerprint, precision regime, backend) — so a fresh
process (or a :class:`~libskylark_tpu.fleet.ProcessReplica` child)
**loads instead of compiling** and serves the same bits from its first
request (docs/performance, "Persistent AOT artifacts & warmup packs").

Safety model:

- **The key is the contract.** Anything that would change the traced
  program changes a key component and therefore the digest — a stale
  artifact can never be *served*, only *ignored*. Invalidation is
  automatic: a plan-cache edit, a code change in the wrapped solver or
  the engine itself, a precision flip, a sharding change each land on
  a fresh digest.
- **Compatibility probing.** The key does not capture the runtime, so
  every artifact carries a compat stamp (schema, jax/jaxlib version,
  backend, device kind, device count) checked before deserialization;
  any mismatch — and any deserialize failure at all — falls back to a
  fresh compile, counted (``aot_load_failures``) and warned once per
  reason, never raised into the caller.
- **Cross-process single-flight.** A cold key takes a per-digest file
  lock before compiling; N racing cold processes elect one compiler
  while the rest block on the lock and then *load* the winner's
  artifact — exactly one backend compile fleet-wide. A lock whose
  holder died (same-host pid probe) or that outlived
  ``SKYLARK_AOT_LOCK_STALE`` seconds is taken over; a lock wait past
  ``SKYLARK_AOT_LOCK_TIMEOUT`` gives up and compiles anyway
  (liveness beats strict exactly-once).

``SKYLARK_AOT_DIR`` names the store (``0``/``off`` disables). The
pre-r13 ``SKYLARK_EXEC_CACHE_DIR`` — which wires jax's persistent
*compilation* cache (tracing still paid, HLO-keyed) — doubles as a
deprecated alias: when only it is set, artifacts go to
``$SKYLARK_EXEC_CACHE_DIR/aot`` with a one-time ``DeprecationWarning``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import socket
import struct
import time
import warnings
from typing import Any, Optional

from libskylark_tpu.base import env as _env

AOT_SCHEMA = 1

_MAGIC = b"SKYAOT1\n"
_SUFFIX = ".skyaot"
# builder-scoped dir override (engine.warmup writes a pack's artifacts
# without touching the process environment)
_DIR_OVERRIDE: Optional[str] = None
_alias_warned = False


class AotLoadError(Exception):
    """An artifact exists but cannot be used (compat mismatch, torn
    file, deserialize failure). ``reason`` is a stable slug the
    failure counters/warnings carry; the caller falls back to a fresh
    compile."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


# ---------------------------------------------------------------------------
# store location + policy
# ---------------------------------------------------------------------------


def aot_dir() -> Optional[str]:
    """The artifact store directory, or None when disabled.
    ``SKYLARK_AOT_DIR`` wins; a set-but-off value disables even when
    the deprecated ``SKYLARK_EXEC_CACHE_DIR`` alias is present."""
    global _alias_warned
    if _DIR_OVERRIDE is not None:
        return _DIR_OVERRIDE
    if _env.AOT_DIR.is_set():
        # set: the parsed value (an off-word parses to None — disabled,
        # and the legacy alias below must NOT resurrect the store)
        return _env.AOT_DIR.get()
    legacy = _env.EXEC_CACHE_DIR.get()
    if legacy:
        if not _alias_warned:
            _alias_warned = True
            warnings.warn(
                "SKYLARK_EXEC_CACHE_DIR without SKYLARK_AOT_DIR: using "
                f"{legacy}/aot for AOT executable artifacts. The "
                "variable is deprecated for this purpose — it keeps "
                "wiring jax's persistent compilation cache; set "
                "SKYLARK_AOT_DIR for the artifact store "
                "(docs/performance).",
                DeprecationWarning, stacklevel=2)
        return os.path.join(legacy, "aot")
    return None


def enabled() -> bool:
    return aot_dir() is not None


@contextlib.contextmanager
def override_dir(path: Optional[str]):
    """Scoped store override (the warmup-pack builder). Not re-entrant
    across threads — builders are offline, single-threaded tools."""
    global _DIR_OVERRIDE
    prev = _DIR_OVERRIDE
    _DIR_OVERRIDE = path
    try:
        yield
    finally:
        _DIR_OVERRIDE = prev


def lock_stale_seconds() -> float:
    return _env.AOT_LOCK_STALE.get()


def lock_timeout() -> float:
    return _env.AOT_LOCK_TIMEOUT.get()


# ---------------------------------------------------------------------------
# addressing + compatibility
# ---------------------------------------------------------------------------


def key_digest(key: Any) -> str:
    """Content address of one executable-cache key. The key tuple is
    built from primitives with stable ``repr`` (strings, ints, bools,
    nested tuples), so its repr is a faithful serialization."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:32]


def compat_stamp() -> dict:
    """The runtime properties an artifact is only valid under — the
    parts of the world the cache key does NOT capture."""
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "unknown")
    except Exception:
        jaxlib_v = "unknown"
    devs = jax.devices()
    return {
        "schema": AOT_SCHEMA,
        "jax": jax.__version__,
        "jaxlib": jaxlib_v,
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "unknown",
        "device_count": len(devs),
    }


_compat_tag_cache: Optional[str] = None


def compat_tag() -> str:
    """Short content hash of this runtime's compat stamp — part of the
    artifact *filename*, so runtimes whose cache keys coincide (same
    backend, different jax/jaxlib/device kind/count) address different
    files in a shared store instead of overwriting each other's
    artifacts on every fallback compile."""
    global _compat_tag_cache
    if _compat_tag_cache is None:
        doc = json.dumps(compat_stamp(), sort_keys=True).encode()
        _compat_tag_cache = hashlib.sha256(doc).hexdigest()[:8]
    return _compat_tag_cache


def compat_probe(stamp: Optional[dict]) -> tuple[bool, Optional[str]]:
    """(ok, why-not) of an artifact/pack stamp against this process."""
    if not isinstance(stamp, dict):
        return False, "no-compat-stamp"
    here = compat_stamp()
    for field in ("schema", "jax", "jaxlib", "backend", "device_kind",
                  "device_count"):
        if stamp.get(field) != here[field]:
            return False, (f"{field}-mismatch "
                           f"({stamp.get(field)!r} != {here[field]!r})")
    return True, None


def artifact_path(digest: str, dirpath: Optional[str] = None) -> str:
    """Where THIS runtime's artifact for ``digest`` lives — the name
    carries the compat tag, so heterogeneous runtimes sharing one
    store coexist instead of thrashing one path."""
    d = dirpath or aot_dir()
    if d is None:
        raise RuntimeError("AOT artifact store is not enabled")
    return os.path.join(d, f"{digest}.{compat_tag()}{_SUFFIX}")


# ---------------------------------------------------------------------------
# artifact file format: MAGIC | u64 header length | JSON header | pickle
# (the header is readable without unpickling — compat probing and pack
# inspection never execute artifact bytes they might reject)
# ---------------------------------------------------------------------------


def save(key: Any, executable: Any, *, name: str,
         compile_seconds: float = 0.0, meta: Optional[dict] = None,
         dirpath: Optional[str] = None) -> Optional[str]:
    """Serialize one compiled executable under its key digest. Never
    raises — persistence is an optimization, not a failure mode; a
    failed save returns None (counted by the caller's store stats).
    The write is atomic (temp + ``os.replace``): a racing reader sees
    the old artifact or the new one, never a torn file."""
    from jax.experimental import serialize_executable as _se

    d = dirpath or aot_dir()
    if d is None:
        return None
    tmp = None
    try:
        os.makedirs(d, exist_ok=True)
        payload, in_tree, out_tree = _se.serialize(executable)
        digest = key_digest(key)
        header = {
            "schema": AOT_SCHEMA,
            "digest": digest,
            "name": name,
            "compat": compat_stamp(),
            "created": time.time(),
            "compile_seconds": round(float(compile_seconds), 4),
            "key_repr": repr(key),
        }
        if meta:
            header.update(meta)
        hdr = json.dumps(header, sort_keys=True).encode()
        path = artifact_path(digest, d)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(_MAGIC)
            fh.write(struct.pack(">Q", len(hdr)))
            fh.write(hdr)
            pickle.dump({"key": key, "payload": payload,
                         "in_tree": in_tree, "out_tree": out_tree},
                        fh, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
        return path
    except Exception as e:  # noqa: BLE001 — never fail the compile path
        if tmp is not None:
            with contextlib.suppress(OSError):
                os.unlink(tmp)    # no orphan .tmp litter in the store
        warnings.warn(f"AOT artifact save failed for {name!r}: {e!r}",
                      RuntimeWarning, stacklevel=2)
        return None


def read_header(path: str) -> dict:
    """The artifact's JSON header (no unpickling). Raises
    :class:`AotLoadError` on a torn/foreign file."""
    try:
        with open(path, "rb") as fh:
            magic = fh.read(len(_MAGIC))
            if magic != _MAGIC:
                raise AotLoadError("bad-magic", path)
            (hlen,) = struct.unpack(">Q", fh.read(8))
            if hlen > 1 << 20:
                raise AotLoadError("oversized-header", path)
            return json.loads(fh.read(hlen))
    except AotLoadError:
        raise
    except FileNotFoundError:
        raise
    except Exception as e:  # noqa: BLE001 — torn file, bad json, ...
        raise AotLoadError("unreadable-header", repr(e)) from e


def load_file(path: str) -> tuple[Any, Any, dict]:
    """``(key, executable, header)`` from one artifact file. Raises
    :class:`AotLoadError` on any compat or deserialize problem and
    ``FileNotFoundError`` on a plain miss."""
    from jax.experimental import serialize_executable as _se

    header = read_header(path)
    ok, why = compat_probe(header.get("compat"))
    if not ok:
        raise AotLoadError("compat", why or "")
    try:
        with open(path, "rb") as fh:
            fh.seek(len(_MAGIC))
            (hlen,) = struct.unpack(">Q", fh.read(8))
            fh.seek(len(_MAGIC) + 8 + hlen)
            doc = pickle.load(fh)
        executable = _se.deserialize_and_load(
            doc["payload"], doc["in_tree"], doc["out_tree"])
    except FileNotFoundError:
        raise                 # a plain miss — the caller compiles
    except Exception as e:  # noqa: BLE001 — deserialize is best-effort;
        # I/O errors (stale NFS handle, permissions) take the same
        # fail-open fallback-to-compile route as a bad pickle — the
        # module contract is that a load failure is never raised into
        # the serve path
        raise AotLoadError("deserialize", repr(e)) from e
    return doc["key"], executable, header


def load(key: Any, dirpath: Optional[str] = None
         ) -> Optional[tuple[Any, dict, float]]:
    """``(executable, header, load_seconds)`` for ``key``, or None when
    no artifact exists. Raises :class:`AotLoadError` when one exists
    but is unusable — the caller counts the failure and compiles."""
    d = dirpath or aot_dir()
    if d is None:
        return None
    path = artifact_path(key_digest(key), d)
    t0 = time.perf_counter()
    try:
        stored_key, executable, header = load_file(path)
        if stored_key != key:
            # a digest collision, or an artifact store shared across
            # incompatible code versions whose digests happened to
            # match — either way the stored program is another key's
            raise AotLoadError("key-mismatch", path)
    except FileNotFoundError:
        return None
    except AotLoadError as e:
        # quarantine genuinely broken files so the store self-heals
        # (every later process would otherwise re-fail on the same
        # bytes); compat mismatches stay — the artifact is valid for
        # the runtime that wrote it (a cpu/tpu- or device-count-
        # heterogeneous fleet sharing one store)
        if e.reason != "compat":
            with contextlib.suppress(OSError):
                os.replace(path, path + ".bad")
        raise
    return executable, header, time.perf_counter() - t0


# ---------------------------------------------------------------------------
# cross-process single-flight: a per-digest advisory file lock
# ---------------------------------------------------------------------------


class FileLock:
    """O_EXCL-based advisory lock with stale-holder takeover.

    The holder writes ``{pid, host, t}`` into the lock file. A waiter
    declares the lock stale — and takes it over — when the recorded
    pid is dead (same host only; a pid means nothing remotely) or the
    file is older than ``stale_seconds`` (the cross-host fallback: a
    compile that outlives it has lost its claim either way). A
    takeover unlink is gated on the judged file's inode identity
    (:meth:`_reap`) so racing reapers cannot remove each other's
    re-created locks, and re-creation resolves at
    ``O_CREAT|O_EXCL``: one contender wins, the rest go back to
    waiting."""

    def __init__(self, path: str, *, stale_seconds: Optional[float] = None,
                 poll: float = 0.05):
        self.path = path
        self.stale_seconds = (lock_stale_seconds()
                              if stale_seconds is None else stale_seconds)
        self.poll = poll
        self.held = False

    def _stale_ident(self) -> Optional[tuple]:
        """The (inode, mtime_ns) of the lock file iff it is stale, else
        None. The identity gates the takeover unlink: a contender may
        only remove the exact file it judged stale, never a lock a
        faster peer re-created at the same path in between."""
        try:
            st = os.stat(self.path)
        except OSError:
            return None           # vanished — the create loop retries
        ident = (st.st_ino, st.st_mtime_ns)
        age = time.time() - st.st_mtime
        if age > self.stale_seconds:
            return ident
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except Exception:  # noqa: BLE001 — holder died mid-write
            return ident if age > 1.0 else None  # a live writer's instant
        pid, host = doc.get("pid"), doc.get("host")
        if host == socket.gethostname() and isinstance(pid, int):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return ident      # holder is gone
            except PermissionError:
                return None       # alive, different uid
        return None

    def _reap(self, ident: tuple) -> None:
        """Unlink the stale lock only if it is still the judged file —
        two waiters that both judged the old lock stale must not
        unlink each other's freshly re-created locks. (The stat/unlink
        pair is not atomic; the residual window needs the same-path
        inode to be recycled within microseconds, and the worst case
        is one duplicate compile, never a wrong result.)"""
        with contextlib.suppress(OSError):
            st = os.stat(self.path)
            if (st.st_ino, st.st_mtime_ns) == ident:
                os.unlink(self.path)

    def acquire(self, timeout: Optional[float] = None) -> bool:
        """Block until held (True) or ``timeout`` elapses (False — the
        caller proceeds without the lock rather than hanging boot)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                ident = self._stale_ident()
                if ident is not None:
                    self._reap(ident)
                    continue
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                time.sleep(self.poll)
                continue
            except OSError:
                return False      # store dir unwritable: degrade
            with os.fdopen(fd, "w") as fh:
                json.dump({"pid": os.getpid(),
                           "host": socket.gethostname(),
                           "t": time.time()}, fh)
            self.held = True
            return True

    def release(self) -> None:
        """Unlink only a lock we still own: a holder whose compile
        outlived ``stale_seconds`` may have been age-reaped and the
        path re-created by the takeover peer — deleting *that* lock
        would cascade a third holder in while the peer still works."""
        if not self.held:
            return
        self.held = False
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except Exception:  # noqa: BLE001 — gone or torn: nothing to free
            return
        if (doc.get("pid") == os.getpid()
                and doc.get("host") == socket.gethostname()):
            with contextlib.suppress(OSError):
                os.unlink(self.path)

    def __enter__(self) -> "FileLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def lock_for(key: Any, dirpath: Optional[str] = None) -> FileLock:
    d = dirpath or aot_dir()
    if d is None:
        raise RuntimeError("AOT artifact store is not enabled")
    # an uncreatable store must not fail the compile path (the same
    # fail-open discipline as save()): acquire() on the impossible
    # path returns False and the caller compiles without the lock
    with contextlib.suppress(OSError):
        os.makedirs(d, exist_ok=True)
    return FileLock(os.path.join(d, key_digest(key) + ".lock"))


def list_artifacts(dirpath: Optional[str] = None) -> list[dict]:
    """Headers of every readable artifact in the store (inspection /
    the warmup CLI); unreadable files are skipped, not raised."""
    d = dirpath or aot_dir()
    if d is None or not os.path.isdir(d):
        return []
    out = []
    for fn in sorted(os.listdir(d)):
        if not fn.endswith(_SUFFIX):
            continue
        try:
            out.append(read_header(os.path.join(d, fn)))
        except Exception:  # noqa: BLE001 — inspection is best-effort
            continue
    return out


__all__ = [
    "AOT_SCHEMA", "AotLoadError", "FileLock", "aot_dir", "artifact_path",
    "compat_probe", "compat_stamp", "enabled", "key_digest",
    "list_artifacts", "load", "load_file", "lock_for", "lock_timeout",
    "override_dir", "read_header", "save",
]
