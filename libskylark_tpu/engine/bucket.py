"""Shape classes for microbatch serving: pow2 pad-and-mask policy.

The serving executor (:mod:`libskylark_tpu.engine.serve`) coalesces
concurrent requests into one vmapped executable per *bucket*. A bucket
is the set of requests that can share a compiled program: same endpoint
statics (sketch family, sketch dim, solve method, kernel digest, ...),
same dtype, and the same **shape class** — every paddable dimension
rounded up to the next power of two (with a floor, so tiny requests
don't fragment into one-off buckets). Two ragged requests in one class
are padded to the class shape with zeros; the endpoints' virtual random
streams are positional, so zero-padding is *bit-exact*, not just
masked-approximate (see ``sketch.dense.serve_apply``).

The batch dimension gets the same treatment: a cohort of k requests
runs at the pow2 **capacity class** ≥ k (clamped to ``max_batch``,
rounded to the mesh's device count when the batch is sharded), with
filler lanes replicating the last real request. Steady-state traffic
therefore compiles one executable per (bucket, capacity class) and
never again — the zero-recompile property the CI serve gate asserts.

The cost of padding is wasted MXU work, tracked by the executor as
``padding_waste`` (1 − real elements / padded elements over the primary
operand). Halving the pow2 growth (``geometric=√2``-style classes)
would halve worst-case waste at the price of ~2× more buckets; the
pow2 default keeps the executable population small, which is what
bounds compile time and cache pressure in a serve-many process.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

# Smallest padded extent: dimensions below this share one class, so a
# flood of tiny requests (the microbatching sweet spot) lands in a
# single bucket instead of one per exact shape.
PAD_FLOOR = 8


def pow2_pad(n: int, floor: int = PAD_FLOOR) -> int:
    """The shape class of extent ``n``: next power of two ≥ max(n, floor)."""
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


def pad_shape(shape: Sequence[int], pad_axes: Sequence[int],
              floor: int = PAD_FLOOR) -> tuple[int, ...]:
    """Round the extents named by ``pad_axes`` up to their pow2 class;
    other extents are exact-match bucket components (e.g. the feature
    dimension of a solve, which cannot be zero-padded without making
    the compressed problem singular)."""
    pad_axes = set(int(a) for a in pad_axes)
    return tuple(
        pow2_pad(e, floor) if i in pad_axes else int(e)
        for i, e in enumerate(shape)
    )


def nnz_class(nnz: int, floor: int = 64) -> int:
    """The **nnz class** of a sparse operand: next power of two ≥
    max(nnz, floor). Sparse serve buckets key on this alongside the
    padded dims/dtype (docs/serving, "Sparse operands on the serve
    path"): two ragged-nnz requests in one class pad their (data,
    indices) lanes to the class extent and coalesce into one flush
    executable — padding entries carry value 0.0 at position 0, which
    contributes exact zeros through every sparse endpoint. The floor
    (``SKYLARK_SPARSE_NNZ_FLOOR``) keeps a flood of tiny sparse
    requests in a single bucket, the same anti-fragmentation role
    ``PAD_FLOOR`` plays for dense extents."""
    return pow2_pad(nnz, max(int(floor), 1))


def capacity_class(k: int, max_batch: int, multiple: int = 1) -> int:
    """Batch capacity for a cohort of ``k`` requests: pow2 ≥ k, clamped
    to ``max_batch``, then rounded up to ``multiple`` (the mesh device
    count when the batch dimension is sharded — every shard must get
    the same lane count)."""
    cap = min(1 << (max(int(k), 1) - 1).bit_length(), int(max_batch))
    m = max(int(multiple), 1)
    cap = ((cap + m - 1) // m) * m
    return max(cap, 1)


def capacity_ladder(max_batch: int, multiple: int = 1) -> tuple:
    """Every capacity class reachable below ``max_batch`` — the pow2
    rungs (rounded to ``multiple``), ascending. Any bucket's *warm*
    capacity set — the rungs it has actually flushed at, which is
    what the adaptive batching controller
    (:mod:`libskylark_tpu.qos.controller`) restricts its batch-target
    moves to — is a subset of this ladder; warmup drivers and
    capacity planning enumerate it to pre-compile the whole set."""
    rungs = []
    k = 1
    while k <= int(max_batch):
        cap = capacity_class(k, max_batch, multiple)
        if not rungs or cap != rungs[-1]:
            rungs.append(cap)
        k <<= 1
    # a non-pow2 max_batch clamps full cohorts to a rung the pow2
    # sweep never visits (capacity_class(12, 12) = 12) — the most
    # common capacity under load must be on the ladder
    top = capacity_class(int(max_batch), max_batch, multiple)
    if top != rungs[-1]:
        rungs.append(top)
    return tuple(rungs)


def stack_pad(arrays: Sequence[np.ndarray], padded_shape: Sequence[int],
              capacity: int, dtype) -> np.ndarray:
    """One host-side (capacity, *padded_shape) buffer holding every
    request's operand zero-padded into its top-left corner, filler
    lanes replicating the last real request (replication, not zeros:
    a zero operand can hit degenerate branches — a singular QR, a NaN
    cond — and a filler lane must cost exactly one real lane, never
    poison the flush). The buffer is freshly allocated per flush: the
    executor donates it to the executable, so reuse across flushes
    would re-read a deleted buffer."""
    padded_shape = tuple(int(e) for e in padded_shape)
    out = np.zeros((int(capacity),) + padded_shape, dtype=dtype)
    for i, a in enumerate(arrays):
        a = np.asarray(a)
        out[(i,) + tuple(slice(0, e) for e in a.shape)] = a
    for i in range(len(arrays), int(capacity)):
        out[i] = out[len(arrays) - 1]
    return out


def padded_elements(padded_shape: Sequence[int], capacity: int) -> int:
    return int(capacity) * int(np.prod([int(e) for e in padded_shape]))


def real_elements(shapes: Sequence[Sequence[int]]) -> int:
    return int(sum(int(np.prod([int(e) for e in s])) for s in shapes))


def result_nbytes(value) -> int:
    """Byte accounting of one serve result for the cache/residency
    quotas (docs/caching): host arrays count their buffer, containers
    sum their array members, anything else counts a conservative
    64-byte overhead. This is the same element-accounting layer the
    padding-waste counters use — quota arithmetic must agree across
    every executor, so it lives here rather than per call site."""
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (tuple, list)):
        return 64 + sum(result_nbytes(v) for v in value)
    if isinstance(value, dict):
        return 64 + sum(result_nbytes(v) for v in value.values())
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    return 64
