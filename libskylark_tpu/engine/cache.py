"""In-process executable cache: LRU over compiled solver programs.

One entry = one XLA executable, AOT-compiled (``jit(...).lower(...)
.compile()``) so an entry can never silently recompile — every backend
compile in the engine goes through :meth:`ExecutableCache.put`, which
makes the cache's own counters *the* compile counters (the jit-leak CI
gate and the recompile-guard tests key off them).

Counter vocabulary:

``hits`` / ``misses``
    lookup outcomes; a miss is always followed by exactly one compile.
``recompiles``
    misses whose key was compiled before in this process — either LRU
    thrash (evicted then needed again) or key churn (a key component
    flapping between two values). The CI jit-leak gate asserts this
    stays 0 across the tier-1 solver tests.
``evictions``
    LRU entries dropped at capacity (``SKYLARK_EXEC_CACHE_SIZE``,
    default 128 executables).
``compiles``
    actual backend (XLA) compiles — misses that were NOT served from
    the persistent AOT artifact store. Without ``SKYLARK_AOT_DIR`` this
    equals ``misses``; with it, a warm store keeps it at 0 (the boot
    gate's "zero backend compiles" reads exactly this).
``aot_loads`` / ``aot_load_failures``
    misses (or warmup-pack boot loads) resolved by deserializing a
    persisted artifact, and artifacts that existed but failed the
    compat probe / deserialize and fell back to a compile.
``compile_seconds`` / ``load_seconds`` / ``execute_seconds``
    cumulative wall time split the bench reports per solver —
    ``load_seconds`` (artifact deserialize) is deliberately separate
    from ``compile_seconds`` so the cold-start A/B is visible in the
    counters themselves.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional

from libskylark_tpu.base import locks as _locks


@dataclasses.dataclass
class EngineStats:
    """Mutable counter block; one global instance plus one per wrapped
    solver (``CompiledFn.stats``)."""

    hits: int = 0
    misses: int = 0
    recompiles: int = 0
    evictions: int = 0
    executions: int = 0
    compiles: int = 0
    aot_loads: int = 0
    aot_load_failures: int = 0
    compile_seconds: float = 0.0
    load_seconds: float = 0.0
    execute_seconds: float = 0.0

    def hit_rate(self) -> Optional[float]:
        n = self.hits + self.misses
        return (self.hits / n) if n else None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["hit_rate"] = self.hit_rate()
        return d

    def reset(self) -> None:
        self.hits = self.misses = self.recompiles = 0
        self.evictions = self.executions = self.compiles = 0
        self.aot_loads = self.aot_load_failures = 0
        self.compile_seconds = self.load_seconds = 0.0
        self.execute_seconds = 0.0

    def merge(self, other: "EngineStats") -> None:
        """Accumulate ``other`` into this block (the lifetime rollup)."""
        self.hits += other.hits
        self.misses += other.misses
        self.recompiles += other.recompiles
        self.evictions += other.evictions
        self.executions += other.executions
        self.compiles += other.compiles
        self.aot_loads += other.aot_loads
        self.aot_load_failures += other.aot_load_failures
        self.compile_seconds += other.compile_seconds
        self.load_seconds += other.load_seconds
        self.execute_seconds += other.execute_seconds


@dataclasses.dataclass
class CacheEntry:
    """One compiled executable plus its provenance."""

    executable: Any           # jax.stages.Compiled (or AOT-deserialized)
    name: str                 # wrapped solver name
    compile_seconds: float
    calls: int = 0
    loaded: bool = False      # deserialized from the AOT artifact store


class ExecutableCache:
    """Thread-safe LRU of :class:`CacheEntry` keyed on the engine's
    static key tuples. ``seen`` remembers every key ever compiled in
    this process so a re-compile of a previously-compiled key (thrash)
    is distinguishable from a first compile.

    Concurrency contract (the serve executor calls ``CompiledFn`` from
    multiple worker threads): every counter increment and every LRU
    order mutation happens under ``_lock``, and a miss is single-flight
    — :meth:`acquire` hands the compile to exactly one thread while
    the others wait on an in-flight event, so N racing threads on a
    cold key produce ONE miss + one compile + N−1 hits, never N
    compiles of the same executable."""

    def __init__(self, maxsize: int = 128):
        self.maxsize = int(maxsize)
        self._entries: "OrderedDict[Hashable, CacheEntry]" = OrderedDict()
        self._seen: set = set()
        self._lock = _locks.make_lock("engine.cache")
        # key -> Event for compiles in flight (single-flight discipline)
        self._inflight: dict = {}
        self.stats = EngineStats()
        # counters folded in at every reset(): the process-lifetime view
        # the CI jit-leak gate reads, immune to tests zeroing `stats`
        self.lifetime = EngineStats()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: Hashable) -> Optional[CacheEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.stats.hits += 1
                return entry
            self.stats.misses += 1
            if key in self._seen:
                self.stats.recompiles += 1
            return None

    def acquire(self, key: Hashable) -> Optional[CacheEntry]:
        """Single-flight lookup: an entry on hit, else ``None`` exactly
        once per cold key — the calling thread owns the compile and MUST
        finish with :meth:`insert` or :meth:`abort`. Concurrent callers
        of the same cold key block until the owner resolves it, then
        take the hit path (or inherit the compile if the owner
        aborted)."""
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.stats.hits += 1
                    return entry
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                    self.stats.misses += 1
                    if key in self._seen:
                        self.stats.recompiles += 1
                    return None
            ev.wait()

    def insert(self, key: Hashable, entry: CacheEntry) -> None:
        with self._lock:
            self._seen.add(key)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self.stats.compile_seconds += entry.compile_seconds
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.stats.evictions += 1
            ev = self._inflight.pop(key, None)
        if ev is not None:
            ev.set()

    def abort(self, key: Hashable) -> None:
        """Release an :meth:`acquire`-owned compile that failed; blocked
        waiters re-race, and the next one inherits the compile."""
        with self._lock:
            ev = self._inflight.pop(key, None)
        if ev is not None:
            ev.set()

    def note_compile(self) -> None:
        """Record one actual backend (XLA) compile — bumped by the
        engine exactly where ``jit(...).lower().compile()`` ran, never
        for an artifact load, so ``compiles`` is the fleet-boot gate's
        "zero backend compiles" counter."""
        with self._lock:
            self.stats.compiles += 1

    def note_aot_load(self, seconds: float) -> None:
        """Record one persisted-artifact deserialize (a miss or a
        warmup-pack boot load resolved without a backend compile)."""
        with self._lock:
            self.stats.aot_loads += 1
            self.stats.load_seconds += seconds

    def note_aot_load_failure(self) -> None:
        """Record one unusable artifact (compat/deserialize failure
        that fell back to a fresh compile)."""
        with self._lock:
            self.stats.aot_load_failures += 1

    def note_execution(self, entry: CacheEntry, seconds: float) -> None:
        """Record one executable dispatch (entry call count + global
        execution counters) atomically."""
        with self._lock:
            entry.calls += 1
            self.stats.executions += 1
            self.stats.execute_seconds += seconds

    def clear(self) -> None:
        """Drop all executables (the ``seen`` set survives — a post-clear
        recompile is still thrash from the gate's point of view; use
        :meth:`reset` for a clean slate)."""
        with self._lock:
            self._entries.clear()

    def reset(self) -> None:
        """Full reset: entries, seen-keys, and counters (tests). The
        window's counters roll into ``lifetime`` first — thrash cannot
        be erased by resetting. In-flight compile events are released so
        a reset mid-compile cannot strand waiters."""
        with self._lock:
            self._entries.clear()
            self._seen.clear()
            self.lifetime.merge(self.stats)
            self.stats.reset()
            inflight = list(self._inflight.values())
            self._inflight.clear()
        for ev in inflight:
            ev.set()

    def keys(self) -> list:
        with self._lock:
            return list(self._entries.keys())

    def snapshot(self) -> list[dict]:
        """Per-entry provenance for bench/debug output."""
        with self._lock:
            return [
                {"name": e.name, "calls": e.calls,
                 "compile_seconds": round(e.compile_seconds, 4),
                 "loaded": e.loaded}
                for e in self._entries.values()
            ]
