"""``engine.compiled`` — whole-solver compilation with an explicit cache.

``compiled(fn, ...)`` wraps a pure solver pipeline in ``jax.jit`` with
explicit static arguments and (opt-in) buffer donation, AOT-compiles it
(``lower().compile()``) and serves the executable from an in-process
LRU (:mod:`libskylark_tpu.engine.cache`). The cache key is explicit —
nothing is left to jit's implicit closure identity, so two *different*
transform objects with the same (seed, counter) share one executable,
and a plan-cache edit (``tune``) invalidates exactly the executables
whose dispatch it could change:

    (solver name, code-version hash, static args, key_fn extras,
     abstract shapes/dtypes, sharding/mesh fingerprint,
     autotuner plan fingerprint, solver-precision regime, backend)

The AOT discipline buys a hard property: an entry can never silently
recompile — ``jax.stages.Compiled`` raises on a signature mismatch
instead of re-tracing — so the engine's miss counter is exactly the
process's solver-compile counter, which the recompile-guard tests and
the CI jit-leak gate rely on.

Donation: callers opt in per-site (``donate_argnums``) and globally
(``SKYLARK_ENGINE_DONATE=1`` flips :func:`donation_enabled`, which the
solver entry points consult via :func:`maybe_donate`). Donated operands
are consumed — the caller's array is invalidated on every backend,
including CPU. The tier-1 default is off because the public solvers
take *user* operands (docs/performance.rst, "donation caveats").

Cross-process reuse has two tiers (docs/performance, "Persistent AOT
artifacts & warmup packs"):

- ``SKYLARK_AOT_DIR=<dir>`` — the **artifact store**
  (:mod:`libskylark_tpu.engine.aot`): every AOT compile is serialized
  under a digest of this exact cache key; a later process *loads
  instead of compiling* (zero tracing, zero backend compile), with
  compat probing and fall-back-to-compile on any deserialize failure,
  and a per-key file lock extending the single-flight discipline
  across processes — N racing cold replicas perform one compile
  fleet-wide.
- ``SKYLARK_EXEC_CACHE_DIR=<dir>`` — jax's persistent *compilation*
  cache (tracing still paid, HLO-keyed), wired at first engine
  compile. Deprecated as an artifact-store alias: when set without
  ``SKYLARK_AOT_DIR``, artifacts additionally land in ``<dir>/aot``
  with a one-time ``DeprecationWarning``.
"""

from __future__ import annotations

import functools
import hashlib
import inspect
import json
import os
import time
import warnings
from typing import Callable, Optional, Sequence

import jax

from libskylark_tpu import telemetry as _telemetry
from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import aot as _aot
from libskylark_tpu.engine.cache import CacheEntry, EngineStats, ExecutableCache
from libskylark_tpu.resilience import faults as _faults

# ---------------------------------------------------------------------------
# global cache + policy switches
# ---------------------------------------------------------------------------


def _cache_size() -> int:
    return _env.EXEC_CACHE_SIZE.get()


_CACHE = ExecutableCache(maxsize=_cache_size())

# telemetry re-homing (docs/observability): the cache's own counters are
# the authoritative compile/hit/miss source — the collector snapshots
# them instead of double-counting on the hot path. Only the cold compile
# (already seconds-scale) opens a span + histogram observation.
_COMPILE_HIST = _telemetry.histogram(
    "engine.compile_seconds",
    "Wall time of cold XLA compiles through the executable cache")
_LOAD_HIST = _telemetry.histogram(
    "engine.load_seconds",
    "Wall time of persisted-AOT-artifact loads (deserialize instead "
    "of compile) through the executable cache")
_PERSIST_FAIL = _telemetry.counter(
    "engine.persistent_cache_failures",
    "enable_persistent_cache attempts that failed (jax persistent "
    "compilation cache could not be wired)")

# one warning per (reason) per process for unusable AOT artifacts —
# the counter carries the volume, the warning carries the diagnosis
_aot_warned: set = set()


def _lifetime_rollup() -> EngineStats:
    """The reset-proof rollup (current window included) — ONE
    implementation for both the telemetry snapshot and the
    ``dump_stats`` artifact the CI jit-leak gate reads, so the two
    views can never desynchronize."""
    lifetime = EngineStats()
    lifetime.merge(_CACHE.lifetime)
    lifetime.merge(_CACHE.stats)
    return lifetime


def _telemetry_engine_block() -> dict:
    return {"stats": _CACHE.stats.to_dict(),
            "lifetime": _lifetime_rollup().to_dict(),
            "cache_entries": len(_CACHE)}


_telemetry.register_collector("engine", _telemetry_engine_block)


def cache() -> ExecutableCache:
    """The process-global executable cache."""
    return _CACHE


def stats() -> EngineStats:
    """Global engine counters (hits/misses/recompiles/compile time)."""
    return _CACHE.stats


def reset() -> None:
    """Drop every executable and zero the counters (tests/benches)."""
    _CACHE.reset()


def donation_enabled() -> bool:
    """Whether solver entry points donate their operands
    (``SKYLARK_ENGINE_DONATE=1``). Off by default: donation invalidates
    the caller's arrays (on every backend, CPU included)."""
    return _env.ENGINE_DONATE.get()


def maybe_donate(argnums: Sequence[int]) -> tuple[int, ...]:
    """``argnums`` when donation is enabled, else ``()`` — the one-line
    policy the solver entry points use for their donate_argnums."""
    return tuple(argnums) if donation_enabled() else ()


# ---------------------------------------------------------------------------
# persistent (cross-process) compilation cache wiring
# ---------------------------------------------------------------------------

_persistent_wired = False


def enable_persistent_cache(path: Optional[str] = None) -> bool:
    """Wire jax's persistent compilation cache at ``path`` (or
    ``SKYLARK_EXEC_CACHE_DIR``). Returns whether wiring happened. Never
    raises — the persistent cache is an optimization, not a failure
    mode."""
    global _persistent_wired
    path = path or _env.EXEC_CACHE_DIR.raw()
    if not path or path.strip().lower() in ("0", "off", "no", "false"):
        return False
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        try:
            # jax memoizes a "cache disabled" decision at the first
            # compile; dropping it makes the next compile re-read the
            # config — without this, wiring after any eager op (key
            # fold_in, a warm-up) is silently a no-op
            from jax.experimental.compilation_cache import compilation_cache

            compilation_cache.reset_cache()
        except Exception:
            pass
        try:
            # lower than bench.py's 1.0s TPU threshold: solver pipeline
            # executables backend-compile in well under a second on CPU
            # hosts yet are exactly the artifacts worth persisting for
            # the serve-many processes
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.1)
        except Exception:
            pass
        _persistent_wired = True
        return True
    except Exception as e:  # noqa: BLE001 — optimization, not failure
        # observable, not silent (r13 satellite): one warning plus an
        # always-on counter, so "the persistent cache never engaged"
        # shows up in telemetry instead of as a mystery cold fleet
        _PERSIST_FAIL.inc_always(reason=type(e).__name__)
        warnings.warn(
            f"jax persistent compilation cache could not be wired at "
            f"{path!r}: {e!r} — continuing without it",
            RuntimeWarning, stacklevel=2)
        return False


def _maybe_wire_persistent() -> None:
    global _persistent_wired
    if not _persistent_wired and _env.EXEC_CACHE_DIR.is_set():
        _persistent_wired = True  # one attempt per process
        enable_persistent_cache()


# ---------------------------------------------------------------------------
# cache-key components
# ---------------------------------------------------------------------------

_code_hashes: dict[str, str] = {}


def _file_hash(path: str) -> str:
    h = _code_hashes.get(path)
    if h is None:
        try:
            with open(path, "rb") as fh:
                h = hashlib.sha256(fh.read()).hexdigest()[:16]
        except OSError:
            h = "unreadable"
        _code_hashes[path] = h
    return h


def code_version(fn: Callable) -> str:
    """Code-version component of the cache key: a hash over the wrapped
    solver's defining module plus the engine's own sources, so editing
    either invalidates persisted executables keyed on it (the
    cross-process analog of "recompile after a code change")."""
    paths = [__file__, os.path.join(os.path.dirname(__file__), "cache.py")]
    try:
        src = inspect.getsourcefile(fn)
        if src:
            paths.append(src)
    except TypeError:
        pass
    return "-".join(_file_hash(p) for p in paths)


def plan_fingerprint() -> str:
    """The autotuner plan cache's content fingerprint
    (:func:`libskylark_tpu.tune.plan_fingerprint` — one implementation,
    re-exported here for the key path): part of every engine key, so a
    certified-plan change triggers — and a no-op write avoids —
    recompilation. Never raises: a broken plan cache must not take down
    a solver call."""
    try:
        from libskylark_tpu import tune

        return tune.plan_fingerprint()
    except Exception:
        return "no-plan-cache"


def digest(obj) -> str:
    """Stable identity of a closed-over collaborator (sketch transform,
    kernel, params block) for ``key_fn`` extras: the hash of its JSON
    serialization when it has one (``to_json`` — transforms serialize
    their (seed, counter) creation context, kernels their
    hyperparameters), else its ``repr``. Two transform *objects* with
    the same serialization are the same pure function of the input —
    and share one executable."""
    try:
        doc = obj.to_json()
    except AttributeError:
        doc = repr(obj)
    return hashlib.sha256(str(doc).encode()).hexdigest()[:16]


def _precision_fingerprint() -> tuple:
    from libskylark_tpu.base import precision

    try:
        ambient = precision.ambient_matmul_precision()
    except Exception:
        ambient = None
    return (precision.get_solver_precision(), str(ambient))


def _aval_key(x) -> tuple:
    shape = tuple(getattr(x, "shape", ()))
    dtype = str(getattr(x, "dtype", type(x).__name__))
    return (shape, dtype)


def _sharding_key(x) -> str:
    try:
        return str(x.sharding)
    except Exception:
        return "unsharded"


# ---------------------------------------------------------------------------
# the wrapper
# ---------------------------------------------------------------------------


class CompiledFn:
    """A solver pipeline bound to the executable cache. Call it like the
    wrapped function; statics go by keyword (``static_argnames``),
    everything positional is a traced array."""

    def __init__(self, fn: Callable, *, static_argnames: Sequence[str] = (),
                 donate_argnums: Sequence[int] = (),
                 donate: str = "explicit",
                 key_fn: Optional[Callable] = None,
                 name: Optional[str] = None):
        if donate not in ("explicit", "auto"):
            raise ValueError(f"donate must be 'explicit' or 'auto', "
                             f"got {donate!r}")
        self._fn = fn
        self._static_argnames = tuple(static_argnames)
        self._donate_argnums = tuple(donate_argnums)
        self._donate_mode = donate
        self._key_fn = key_fn
        self.name = name or getattr(fn, "__qualname__", repr(fn))
        self.stats = EngineStats()
        # per-wrapper counters are bumped from serve worker threads too;
        # bare += on a dataclass field is a read-modify-write race
        self._stats_lock = _locks.make_lock("engine.fn_stats")
        self._code_version = None
        functools.update_wrapper(self, fn)

    # -- key --

    def _effective_donate(self) -> tuple[int, ...]:
        """``donate="auto"`` sites (the public solver entry points)
        donate only when the user opted in (SKYLARK_ENGINE_DONATE=1);
        "explicit" sites always honor their argnums. The effective
        tuple is part of the cache key — flipping the opt-in mid-
        process keys a fresh executable rather than mis-serving one
        with the wrong aliasing contract."""
        if self._donate_mode == "auto" and not donation_enabled():
            return ()
        return self._donate_argnums

    def _key(self, args, statics, kwargs, donate_argnums) -> tuple:
        if self._code_version is None:
            self._code_version = code_version(self._fn)
        extra = self._key_fn(*args, **kwargs) if self._key_fn else ()
        return (
            self.name,
            self._code_version,
            statics,
            extra,
            tuple(_aval_key(a) for a in args),
            tuple(_sharding_key(a) for a in args),
            donate_argnums,
            plan_fingerprint(),
            _precision_fingerprint(),
            jax.default_backend(),
        )

    # -- cold-key materialization: AOT load > single-flight compile --

    def _aot_load_entry(self, key) -> Optional[CacheEntry]:
        """Deserialize the key's persisted artifact into a cache entry
        (None on plain miss). An artifact that exists but is unusable
        — compat mismatch, torn file, deserialize failure — counts an
        ``aot_load_failures``, warns once per reason, and returns None
        so the caller compiles fresh."""
        try:
            got = _aot.load(key)
        except _aot.AotLoadError as e:
            with self._stats_lock:
                self.stats.aot_load_failures += 1
            _CACHE.note_aot_load_failure()
            if e.reason not in _aot_warned:
                _aot_warned.add(e.reason)
                warnings.warn(
                    f"persisted AOT artifact for {self.name!r} is "
                    f"unusable ({e}); recompiling", RuntimeWarning,
                    stacklevel=3)
            return None
        if got is None:
            return None
        executable, _header, dt = got
        _LOAD_HIST.observe_always(dt, name=self.name)
        with self._stats_lock:
            self.stats.aot_loads += 1
            self.stats.load_seconds += dt
        _CACHE.note_aot_load(dt)
        return CacheEntry(executable=executable, name=self.name,
                          compile_seconds=0.0, loaded=True)

    def _materialize(self, key, args, kwargs, donate_argnums) -> CacheEntry:
        """Resolve one cold key, owning the in-process single-flight:
        load the persisted artifact if the store has it; otherwise take
        the cross-process file lock (so N racing cold *processes*
        produce one compile fleet-wide — a lock wait usually ends with
        the winner's artifact ready to load), and only then compile —
        serializing the result back into the store for the next
        process. The caller aborts the in-process single-flight on any
        raise; the file lock is released here either way."""
        lock = None
        try:
            if _aot.enabled():
                had_artifact = os.path.exists(
                    _aot.artifact_path(_aot.key_digest(key)))
                entry = self._aot_load_entry(key)
                if entry is not None:
                    _CACHE.insert(key, entry)
                    return entry
                lock = _aot.lock_for(key)
                if (lock.acquire(timeout=_aot.lock_timeout())
                        and not had_artifact):
                    # the wait may have spanned a peer's compile+save:
                    # re-probe before compiling ourselves. Skip it when
                    # an artifact was already present and judged
                    # unusable — re-reading the same bytes would only
                    # double-count the failure
                    entry = self._aot_load_entry(key)
                    if entry is not None:
                        _CACHE.insert(key, entry)
                        return entry
                # acquire timeout: compile anyway (liveness) but skip
                # the save — we are not the elected single writer
            entry = self._backend_compile(key, args, kwargs,
                                          donate_argnums)
            if lock is not None and lock.held:
                _aot.save(key, entry.executable, name=self.name,
                          compile_seconds=entry.compile_seconds)
            _CACHE.insert(key, entry)
            return entry
        finally:
            if lock is not None:
                lock.release()

    def _backend_compile(self, key, args, kwargs,
                         donate_argnums) -> CacheEntry:
        t0 = time.perf_counter()
        # chaos seam: a compile-path fault takes the same abort
        # route as a real XLA failure, so injection exercises
        # the single-flight waiter-release contract too
        with _telemetry.span("engine.compile",
                             attrs={"name": self.name}):
            _faults.check("engine.compile", detail=self.name)
            jitted = jax.jit(
                self._fn,
                static_argnames=self._static_argnames or None,
                donate_argnums=donate_argnums or None,
            )
            executable = jitted.lower(*args, **kwargs).compile()
        dt = time.perf_counter() - t0
        # always recorded: compiles are seconds-scale (the
        # histogram bump is noise) and the bench snapshot embeds
        # compile-time data even with telemetry off
        _COMPILE_HIST.observe_always(dt, name=self.name)
        with self._stats_lock:
            self.stats.compiles += 1
            self.stats.compile_seconds += dt
        _CACHE.note_compile()
        return CacheEntry(executable=executable, name=self.name,
                          compile_seconds=dt)

    # -- call --

    def __call__(self, *args, **kwargs):
        import jax.numpy as jnp

        statics = tuple(
            (k, kwargs[k]) for k in self._static_argnames if k in kwargs
        )
        unknown = set(kwargs) - set(self._static_argnames)
        if unknown:
            raise TypeError(
                f"engine.compiled({self.name}): dynamic arguments must be "
                f"positional; got keyword {sorted(unknown)!r}")
        args = tuple(
            a if isinstance(a, jax.Array) else jnp.asarray(a) for a in args
        )
        donate_argnums = self._effective_donate()
        key = self._key(args, statics, kwargs, donate_argnums)
        # single-flight: on a cold key exactly one thread materializes
        # (AOT artifact load, else compile) while concurrent callers of
        # the same key block in acquire()
        entry = _CACHE.acquire(key)
        if entry is None:
            with self._stats_lock:
                self.stats.misses += 1
            _maybe_wire_persistent()
            try:
                entry = self._materialize(key, args, kwargs,
                                          donate_argnums)
            except BaseException:
                _CACHE.abort(key)
                raise
        else:
            with self._stats_lock:
                self.stats.hits += 1
        t0 = time.perf_counter()
        out = entry.executable(*args)
        dt = time.perf_counter() - t0  # dispatch wall; async past this
        with self._stats_lock:
            self.stats.executions += 1
            self.stats.execute_seconds += dt
        _CACHE.note_execution(entry, dt)
        return out


def compiled(fn: Optional[Callable] = None, *,
             static_argnames: Sequence[str] = (),
             donate_argnums: Sequence[int] = (),
             donate: str = "explicit",
             key_fn: Optional[Callable] = None,
             name: Optional[str] = None):
    """Wrap ``fn`` (usable as a decorator) in the donation-aware
    executable cache. See the module docstring for key anatomy."""
    if fn is None:
        return functools.partial(
            compiled, static_argnames=static_argnames,
            donate_argnums=donate_argnums, donate=donate, key_fn=key_fn,
            name=name)
    return CompiledFn(fn, static_argnames=static_argnames,
                      donate_argnums=donate_argnums, donate=donate,
                      key_fn=key_fn, name=name)


# ---------------------------------------------------------------------------
# stats dump (CI jit-leak gate)
# ---------------------------------------------------------------------------


def dump_stats(path: str) -> None:
    """Write global counters + per-entry snapshot as JSON, atomically
    (temp file + ``os.replace`` — the CI jit-leak gate reads this at
    process exit and must never see a torn artifact). ``lifetime`` is
    the reset-proof rollup (current window included) — what the gate
    keys off; ``telemetry`` is the unified registry snapshot
    (docs/observability) so the artifact carries the serve/resilience/
    tune/io counters alongside the engine's own."""
    doc = {"stats": _CACHE.stats.to_dict(),
           "lifetime": _lifetime_rollup().to_dict(),
           "entries": _CACHE.snapshot(),
           "cache_size": len(_CACHE)}
    try:
        from libskylark_tpu.engine.serve import serve_stats

        doc["serve"] = serve_stats()
    except Exception:
        pass
    try:
        doc["telemetry"] = _telemetry.snapshot()
    except Exception:
        pass
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def _install_stats_dump() -> None:
    path = _env.ENGINE_STATS_DUMP.get()
    if not path:
        return
    import atexit

    atexit.register(lambda: _try_dump(path))


def _try_dump(path: str) -> None:
    try:
        dump_stats(path)
    except Exception:
        pass


_install_stats_dump()
