"""Content-addressed result caching, single-flight request dedupe and
operand residency for the serve path (docs/caching).

Every serve endpoint is a **pure function** of (operand bytes, key
material, bucket statics) — the determinism discipline the serve layer
enforces (zero-padding is bit-exact, filler lanes replicate real
requests, seeds ride explicit key data). That purity makes results
*content-addressable*: a blake2b digest over the request's operand
bytes plus its statics names the result uniquely, so a hot operand
storm — a million callers hitting the same matrix — can be served by
ONE flush and a fan-out instead of a million recomputations. This is
the serving analogue of libSkylark's sketch-reuse idiom (sketch once,
solve many); see PAPER.md's nla layer and docs/caching.

Three cooperating mechanisms, layered router → executor → engine:

**Digests** (:func:`operand_digest`). blake2b-256 over a canonical
walk of the request's operand arrays: per array a small header
(name, dtype, shape) followed by the raw buffer. C-contiguous arrays
— including the read-only zero-copy views the r15 SHM transport hands
out, and the (data, indices, indptr) parts of r18 CSR operands — hash
straight from their buffer with **no densify and no staging copy**;
only a non-contiguous view pays a materialization. The digest of a
request must cover everything that reaches the executable: operand
bytes AND the transform's key data (the seed) AND any scale — same
bytes with a different seed is a DIFFERENT request, and coalescing
them would fan one seed's result to the other's caller (the
miscoalesce regression the test battery pins).

**Single-flight** (:meth:`ResultCache.join_flight` /
:meth:`~ResultCache.lead_flight` / :meth:`~ResultCache.settle_flight`).
Concurrent identical requests coalesce onto one in-flight *leader*;
followers get their own futures, and the leader's resolution fans the
one result (or the one exception — a poisoned flush fails every
coalesced waiter identically, never strands a future) out to all of
them. A flight older than ``SKYLARK_CACHE_SINGLE_FLIGHT_TIMEOUT``
stops accepting followers, so a wedged leader cannot accrete waiters
forever.

**Bounded digest→result cache** (:class:`ResultCache`). Byte-budgeted
(``SKYLARK_CACHE_MAX_BYTES``) and partitioned across the r19 QoS
classes by the ``SKYLARK_CACHE_QUOTA_*`` fractions
(:func:`libskylark_tpu.qos.tenants.cache_quota_fraction`). Quotas are
**hard partitions**: inserting into one class evicts only that class's
own oldest entries, so a best_effort tenant can never evict an
interactive working set. Eviction is deterministic — strict insertion
order (FIFO) within the class, no recency reordering — so two
replicas fed the same request history hold bit-identical caches (the
property that makes cross-replica affinity misses cheap). Cached
values are stored as **read-only** host arrays and handed out without
copying: a hit costs a dict lookup, and immutability is what makes
the zero-copy fan-out sound.

**Operand residency** (:class:`ResidencyTable`). ``register_operand``
content-hashes an operand once and pins it (optionally with its
precomputed sketch) under its digest; later submits reference the
:class:`OperandRef` instead of re-shipping bytes, and a pinned sketch
satisfies a matching sketch-apply without touching the flush path at
all. Cross-replica, the fleet layer pushes pins over the SHM
transport with the pickle pipe as fallback (fleet/replica.py).

The cache deliberately does nothing under a DEGRADED executor: the
executor checks its own health *before* touching any cache lock, so a
shedding replica never blocks intake on cache bookkeeping
(docs/caching, "DEGRADED bypass").
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import bucket as bucketing
from libskylark_tpu.qos import tenants as _qtenants
from libskylark_tpu.telemetry import metrics as _metrics

# result-cache instruments (docs/caching) — created HERE once (the
# metric-names one-creation-site contract); per-executor
# disaggregation lives in ``MicrobatchExecutor.stats()["cache"]`` and
# the cross-executor rollup rides the ``cache`` collector registered
# in engine/serve.py.
_HITS = _metrics.counter(
    "cache.hits",
    "Result-cache hits (request served from the digest->result "
    "cache, no flush), by priority class")
_MISSES = _metrics.counter(
    "cache.misses",
    "Result-cache misses (request went on to flush or coalesce), by "
    "priority class")
_BYTES_SAVED = _metrics.counter(
    "cache.bytes_saved",
    "Result bytes served without recomputation — cache hits plus "
    "single-flight fan-outs — by priority class")
_EVICTED = _metrics.counter(
    "cache.evicted",
    "Cache entries evicted by the per-class byte quotas (FIFO within "
    "the inserting class — one class never evicts another's working "
    "set), by priority class")
_SF_COALESCED = _metrics.counter(
    "cache.single_flight_coalesced",
    "Requests coalesced onto an identical in-flight leader (one "
    "flush, N futures), by priority class")
_RESIDENT = _metrics.gauge(
    "cache.resident_operands",
    "Operands currently pinned by register_operand, by replica")


# ---------------------------------------------------------------------------
# digesting
# ---------------------------------------------------------------------------


def _hash_array(h, name: str, a) -> None:
    """Fold one operand array into the digest: a type/shape header
    (two arrays with the same bytes but different dtype or shape must
    not collide) followed by the raw buffer. C-contiguous arrays —
    the steady state: fresh host operands, SHM views, packed CSR
    lanes — feed blake2b through a zero-copy memoryview; only a
    strided view pays ``tobytes()``."""
    a = np.asarray(a)
    h.update(f"|{name}:{a.dtype.str}:{a.shape}|".encode())
    if a.flags.c_contiguous:
        h.update(a.data)
    else:
        h.update(a.tobytes())


def operand_digest(parts, statics=()) -> str:
    """The content address of one request: blake2b-256 over the
    bucket ``statics`` (endpoint, family digest, dtype, shape class —
    everything the executable is keyed on) and ``parts``, an ordered
    sequence of ``(name, value)`` pairs where each value is an
    ndarray-coercible operand, ``bytes``, or ``str``. The caller
    chooses the parts; the serve layer's ``request_digest`` includes
    the transform key data and scale next to the operand bytes so a
    seed change always changes the digest (the miscoalesce
    regression). Order is significant and part names are framed, so
    two part lists cannot collide by concatenation."""
    h = hashlib.blake2b(digest_size=32)
    h.update(repr(tuple(statics)).encode())
    for name, v in parts:
        if isinstance(v, (bytes, bytearray)):
            h.update(f"|{name}:bytes:{len(v)}|".encode())
            h.update(v)
        elif isinstance(v, str):
            h.update(f"|{name}:str|".encode())
            h.update(v.encode())
        elif v is None:
            h.update(f"|{name}:none|".encode())
        else:
            _hash_array(h, name, v)
    return h.hexdigest()


class OperandRef(str):
    """A registered operand's handle: the digest string, typed so the
    serve layer can tell a reference from a real operand at intake.
    Subclassing ``str`` keeps it trivially picklable over the process
    replica pipe (it arrives as the digest text either way — the
    executor re-wraps)."""

    __slots__ = ()

    @property
    def digest(self) -> str:
        return str(self)


def is_ref(x) -> bool:
    """Whether an intake operand is a residency reference (an
    :class:`OperandRef`, or its pickled/forwarded plain-string form
    carrying the ``ref:`` prefix)."""
    return isinstance(x, OperandRef) or (
        isinstance(x, str) and x.startswith("ref:"))


def as_ref(x) -> "OperandRef":
    return x if isinstance(x, OperandRef) else OperandRef(
        x[4:] if isinstance(x, str) and x.startswith("ref:") else x)


# ---------------------------------------------------------------------------
# value freezing + sizing
# ---------------------------------------------------------------------------


def freeze_result(value):
    """An immutable private copy of one result: host arrays are copied
    once and marked read-only; containers are frozen memberwise
    (tuples stay tuples, lists become tuples). The copy detaches the
    cache from the executor's shared batch buffer (``_unpad`` hands
    out views into one donated-flush output), and the read-only flag
    is what lets every later hit and fan-out share the SAME array
    with zero copies — a caller cannot poison the cache through it."""
    if isinstance(value, np.ndarray):
        out = np.array(value, copy=True)
        out.setflags(write=False)
        return out
    if isinstance(value, (tuple, list)):
        return tuple(freeze_result(v) for v in value)
    if isinstance(value, dict):
        return {k: freeze_result(v) for k, v in value.items()}
    return value


class _Flight:
    """One in-flight single-flight entry: the leader's future plus the
    followers fanned from it. Mutated only under the cache lock; the
    fan itself runs outside it (a follower's done-callbacks must not
    execute under cache state)."""

    __slots__ = ("key", "cls", "leader", "followers", "t0", "settled")

    def __init__(self, key: str, cls: str, leader: Future):
        self.key = key
        self.cls = cls
        self.leader = leader
        self.followers: list = []
        self.t0 = time.monotonic()
        self.settled = False


#: lookup's distinguished miss sentinel (``None`` is a legal result)
MISS = object()


class ResultCache:
    """Bounded, class-partitioned digest→result cache with the
    single-flight table (module docstring). One instance per
    :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor`; the
    executor owns the DEGRADED bypass (it never calls in here while
    degraded), this class owns determinism and the quota contract.

    Thread-safety: one leaf lock (``cache.state``) guards the maps;
    no method calls back into the executor or resolves a future while
    holding it, so the lock-order witness stays acyclic by
    construction."""

    def __init__(self, name: str = "",
                 max_bytes: Optional[int] = None,
                 quota_fractions: Optional[Dict[str, float]] = None,
                 single_flight_timeout: Optional[float] = None):
        self.name = str(name)
        self.max_bytes = int(max_bytes if max_bytes is not None
                             else _env.CACHE_MAX_BYTES.get())
        fr = {c: _qtenants.cache_quota_fraction(c)
              for c in _qtenants.CLASSES}
        if quota_fractions:
            for c, f in quota_fractions.items():
                fr[_qtenants.coerce_class(c)] = min(max(float(f), 0.0),
                                                    1.0)
        self.budgets = {c: int(self.max_bytes * fr[c])
                        for c in _qtenants.CLASSES}
        self.sf_timeout = float(
            single_flight_timeout if single_flight_timeout is not None
            else _env.CACHE_SINGLE_FLIGHT_TIMEOUT.get())
        self._lock = _locks.make_lock("cache.state")
        # per class, strict insertion order: FIFO eviction with no
        # recency reordering is what makes two replicas' caches
        # bit-identical under the same request history
        self._entries: Dict[str, "collections.OrderedDict"] = {
            c: collections.OrderedDict() for c in _qtenants.CLASSES}
        self._bytes: Dict[str, int] = {c: 0 for c in _qtenants.CLASSES}
        self._flights: Dict[str, _Flight] = {}
        self._counts: "collections.Counter" = collections.Counter()

    # -- lookup / insert ----------------------------------------------

    def note_hit(self, cls: str, value) -> None:
        """Account a request satisfied from a *pinned* result (an
        operand registered with its transform — the residency table's
        sketch-stage skip): same hit/bytes-saved ledger as a cache
        hit, no entry touched (pins live outside the byte quotas)."""
        cls = _qtenants.coerce_class(cls)
        nbytes = bucketing.result_nbytes(value)
        with self._lock:
            self._counts[("hits", cls)] += 1
            self._counts[("bytes_saved", cls)] += nbytes
        _HITS.inc(**{"class": cls})
        _BYTES_SAVED.inc(nbytes, **{"class": cls})

    def lookup(self, key: str, cls: str):
        """The cached result under ``key`` (a read-only shared value)
        or :data:`MISS`. Counts the hit and the bytes it saved; a
        MISS is counted by :meth:`lead_flight` instead — a request
        that goes on to *coalesce* onto an in-flight leader never
        flushed, so counting it as a miss would make a perfectly
        deduped storm read as a 0% hit rate. The inserting class does
        not gate the lookup — a result is a pure function of the
        request, so serving an interactive hit from a best_effort
        insertion is free sharing, not a quota violation (quotas
        bound *retention*, not reads)."""
        with self._lock:
            for c in _qtenants.CLASSES:
                ent = self._entries[c].get(key)
                if ent is not None:
                    value, nbytes = ent
                    self._counts[("hits", cls)] += 1
                    self._counts[("bytes_saved", cls)] += nbytes
                    break
            else:
                return MISS
        _HITS.inc(**{"class": cls})
        _BYTES_SAVED.inc(nbytes, **{"class": cls})
        return value

    def put(self, key: str, cls: str, value) -> bool:
        """Insert one frozen result under its digest, charged to
        ``cls``'s byte quota; evicts the class's own oldest entries
        (and only those) until the insertion fits. Returns whether
        the value was admitted — one larger than the whole class
        budget is refused (counted ``uncacheable``), never thrashes
        the class clean for a value that cannot stay."""
        cls = _qtenants.coerce_class(cls)
        nbytes = bucketing.result_nbytes(value)
        budget = self.budgets.get(cls, 0)
        evicted = 0
        with self._lock:
            if nbytes > budget:
                self._counts[("uncacheable", cls)] += 1
                return False
            d = self._entries[cls]
            if key in d:            # leader raced a peer insert
                return True
            while self._bytes[cls] + nbytes > budget and d:
                _, (_, old_nb) = d.popitem(last=False)
                self._bytes[cls] -= old_nb
                evicted += 1
            d[key] = (value, nbytes)
            self._bytes[cls] += nbytes
            if evicted:
                self._counts[("evicted", cls)] += evicted
            self._counts[("insertions", cls)] += 1
        if evicted:
            _EVICTED.inc(evicted, **{"class": cls})
        return True

    def invalidate(self, key: str) -> bool:
        """Drop one digest from every class partition (docs/caching,
        "Invalidation"): the serve results themselves never go stale
        — endpoints are pure — but an unpinned resident operand's
        digest may be re-registered with different bytes, and tooling
        that re-seeds a cache wants a surgical drop."""
        dropped = False
        with self._lock:
            for c in _qtenants.CLASSES:
                ent = self._entries[c].pop(key, None)
                if ent is not None:
                    self._bytes[c] -= ent[1]
                    dropped = True
        return dropped

    def clear(self) -> None:
        with self._lock:
            for c in _qtenants.CLASSES:
                self._entries[c].clear()
                self._bytes[c] = 0

    # -- single-flight -------------------------------------------------

    def join_flight(self, key: str, cls: str) -> Optional[Future]:
        """Attach to an identical in-flight request, if one exists and
        is still fresh: returns the follower's future (resolved by
        the leader's settle) or ``None`` (the caller becomes — or
        races to become — the leader). A flight past the
        single-flight timeout no longer accepts followers; it still
        settles the ones it has."""
        with self._lock:
            fl = self._flights.get(key)
            if (fl is None or fl.settled
                    or time.monotonic() - fl.t0 > self.sf_timeout):
                return None
            f: Future = Future()
            fl.followers.append(f)
            self._counts[("single_flight_coalesced", cls)] += 1
            self._counts[("bypassed", cls)] += 1
        _SF_COALESCED.inc(**{"class": cls})
        return f

    def lead_flight(self, key: str, cls: str, leader: Future) -> _Flight:
        """Register ``leader`` as the flight for ``key``; this is also
        where the MISS is counted — the leader is the one request of
        its digest that actually flushes. An existing stale flight is
        displaced (it keeps — and will settle — its own followers; it
        simply stops being joinable)."""
        cls = _qtenants.coerce_class(cls)
        fl = _Flight(key, cls, leader)
        with self._lock:
            self._flights[key] = fl
            self._counts[("misses", cls)] += 1
        _MISSES.inc(**{"class": cls})
        return fl

    def settle_flight(self, flight: _Flight, fut: Future,
                      insert: bool = True) -> None:
        """The leader's done-callback target: detach the flight, cache
        the result (a frozen copy; skipped when the executor is
        DEGRADED — ``insert=False`` — or the leader failed), and fan
        the outcome to every follower. Futures are resolved OUTSIDE
        the cache lock: a follower's own done-callbacks run at
        arbitrary client code, which must never execute under cache
        state. Every follower settles exactly once — a poisoned flush
        fans its exception to all coalesced waiters, orphaning none."""
        with self._lock:
            if flight.settled:
                return
            flight.settled = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
            followers = list(flight.followers)
        exc = fut.exception()
        if exc is not None:
            for f in followers:
                f.set_exception(exc)
            return
        value = fut.result()
        frozen = freeze_result(value)
        nbytes = bucketing.result_nbytes(frozen)
        if insert:
            self.put(flight.key, flight.cls, frozen)
        if followers:
            with self._lock:
                self._counts[("bytes_saved", flight.cls)] += (
                    nbytes * len(followers))
            _BYTES_SAVED.inc(nbytes * len(followers),
                             **{"class": flight.cls})
            for f in followers:
                f.set_result(frozen)

    def abort_flight(self, flight: _Flight, exc: BaseException) -> None:
        """Fail a flight whose leader never reached execution (its
        submit raised synchronously — a shed, an expired deadline):
        the followers coalesced onto a request that no longer exists,
        so they fail with the leader's exception, orphan-free."""
        with self._lock:
            if flight.settled:
                return
            flight.settled = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
            followers = list(flight.followers)
        for f in followers:
            f.set_exception(exc)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        """The ``stats()["cache"]`` block (docs/caching): hit/miss/
        eviction counters and byte budgets per class, live entry
        counts, single-flight accounting. Aggregated across executors
        by :func:`libskylark_tpu.engine.serve.cache_stats` (the
        ``cache`` collector)."""
        with self._lock:
            c = dict(self._counts)
            entries = {cls: len(self._entries[cls])
                       for cls in _qtenants.CLASSES}
            nbytes = dict(self._bytes)
            flights = len(self._flights)

        def total(kind):
            return sum(n for (k, _cls), n in c.items() if k == kind)

        by_class = {}
        for cls in _qtenants.CLASSES:
            by_class[cls] = {
                "hits": c.get(("hits", cls), 0),
                "misses": c.get(("misses", cls), 0),
                "bytes_saved": c.get(("bytes_saved", cls), 0),
                "evicted": c.get(("evicted", cls), 0),
                "single_flight_coalesced": c.get(
                    ("single_flight_coalesced", cls), 0),
                "insertions": c.get(("insertions", cls), 0),
                "uncacheable": c.get(("uncacheable", cls), 0),
                "entries": entries[cls],
                "bytes": nbytes[cls],
                "budget_bytes": self.budgets[cls],
            }
        hits, misses = total("hits"), total("misses")
        return {
            "hits": hits,
            "misses": misses,
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
            "bytes_saved": total("bytes_saved"),
            "evicted": total("evicted"),
            "single_flight_coalesced": total("single_flight_coalesced"),
            "insertions": total("insertions"),
            "uncacheable": total("uncacheable"),
            "entries": sum(entries.values()),
            "bytes": sum(nbytes.values()),
            "max_bytes": self.max_bytes,
            "in_flight": flights,
            "by_class": by_class,
        }


def merge_cache_blocks(blocks) -> dict:
    """Cross-executor merge of per-executor ``stats()["cache"]``
    blocks — counters and byte gauges sum, budgets sum (the process's
    total retention capacity), hit rate re-derives from the pooled
    counts (a mean of per-replica ratios would weight an idle replica
    equally with a loaded one). Shared by ``serve_stats()`` and the
    ``cache`` telemetry collector so the semantics cannot drift."""
    agg: "collections.Counter" = collections.Counter()
    res: "collections.Counter" = collections.Counter()
    by_class: dict = {c: collections.Counter()
                      for c in _qtenants.CLASSES}
    n = 0
    for b in blocks:
        if not b:
            continue
        n += 1
        for k in ("hits", "misses", "bytes_saved", "evicted",
                  "single_flight_coalesced", "insertions",
                  "uncacheable", "entries", "bytes", "max_bytes",
                  "in_flight"):
            agg[k] += b.get(k, 0)
        for cls, blk in b.get("by_class", {}).items():
            by_class[cls].update(blk)
        res.update(b.get("residency") or {})
    out = dict(agg)
    out["caches"] = n
    out["residency"] = dict(res)
    out["hit_rate"] = (
        round(agg["hits"] / (agg["hits"] + agg["misses"]), 4)
        if agg["hits"] + agg["misses"] else None)
    out["by_class"] = {c: dict(by_class[c]) for c in _qtenants.CLASSES}
    return out


class SingleFlight:
    """A standalone flight table — request coalescing WITHOUT the
    result cache. The fleet router uses one per router (docs/caching,
    "Single-flight at the front door"): concurrent identical submits
    coalesce onto one dispatched leader, its result fans to every
    follower, and nothing is retained afterward — replica-side caching
    (and its quota arithmetic, including MISS accounting) stays with
    the executor's :class:`ResultCache`. A coalesced follower here is
    counted on the shared ``cache.single_flight_coalesced`` /
    ``cache.bytes_saved`` instruments; misses are NOT counted (a
    leader that dispatches is an ordinary routed request).

    Same locking discipline as the cache: one leaf lock, futures
    resolved outside it."""

    def __init__(self, name: str = "",
                 timeout: Optional[float] = None):
        self.name = str(name)
        self.timeout = float(
            timeout if timeout is not None
            else _env.CACHE_SINGLE_FLIGHT_TIMEOUT.get())
        self._lock = _locks.make_lock("cache.router_flights")
        self._flights: Dict[str, _Flight] = {}
        self._counts: "collections.Counter" = collections.Counter()

    def join(self, key: str, cls: str) -> Optional[Future]:
        """A follower future for an in-flight ``key``, or ``None``
        (the caller leads). Semantics match
        :meth:`ResultCache.join_flight`: settled or timed-out flights
        no longer accept followers."""
        cls = _qtenants.coerce_class(cls)
        with self._lock:
            fl = self._flights.get(key)
            if (fl is None or fl.settled
                    or time.monotonic() - fl.t0 > self.timeout):
                return None
            f: Future = Future()
            fl.followers.append(f)
            self._counts[("coalesced", cls)] += 1
        _SF_COALESCED.inc(**{"class": cls})
        return f

    def lead(self, key: str, cls: str) -> _Flight:
        """Register the caller as ``key``'s leader (displacing a stale
        flight, which keeps its own followers)."""
        cls = _qtenants.coerce_class(cls)
        fl = _Flight(key, cls, None)
        with self._lock:
            self._flights[key] = fl
            self._counts[("led", cls)] += 1
        return fl

    def settle(self, flight: _Flight, fut: Future) -> None:
        """The leader future's done-callback target: fan the outcome
        (a frozen copy on success — followers at the front door may be
        different tenants and must not share a writable buffer with
        the leader) to every follower. Nothing is cached."""
        with self._lock:
            if flight.settled:
                return
            flight.settled = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
            followers = list(flight.followers)
        if not followers:
            return
        exc = fut.exception()
        if exc is not None:
            for f in followers:
                f.set_exception(exc)
            return
        frozen = freeze_result(fut.result())
        nbytes = bucketing.result_nbytes(frozen)
        with self._lock:
            self._counts[("bytes_saved", flight.cls)] += (
                nbytes * len(followers))
        _BYTES_SAVED.inc(nbytes * len(followers),
                         **{"class": flight.cls})
        for f in followers:
            f.set_result(frozen)

    def abort(self, flight: _Flight, exc: BaseException) -> None:
        """Fail a flight whose leader's dispatch raised synchronously
        (no healthy replica, quota refusal): followers fail with the
        leader's exception, orphan-free."""
        with self._lock:
            if flight.settled:
                return
            flight.settled = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
            followers = list(flight.followers)
        for f in followers:
            f.set_exception(exc)

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            flights = len(self._flights)

        def total(kind):
            return sum(n for (k, _cls), n in c.items() if k == kind)

        return {
            "coalesced": total("coalesced"),
            "led": total("led"),
            "bytes_saved": total("bytes_saved"),
            "in_flight": flights,
            "by_class": {
                cls: {"coalesced": c.get(("coalesced", cls), 0),
                      "led": c.get(("led", cls), 0),
                      "bytes_saved": c.get(("bytes_saved", cls), 0)}
                for cls in _qtenants.CLASSES},
        }


# ---------------------------------------------------------------------------
# operand residency
# ---------------------------------------------------------------------------


class ResidencyTable:
    """Digest→pinned-operand table behind ``register_operand``
    (docs/caching, "Operand residency"). A pin holds the operand's
    frozen host array — and, when registered with a transform, the
    operand's precomputed sketch keyed by the transform's key data —
    for as long as the caller keeps it registered: pins are explicit
    API state, never evicted by the byte quotas (the cache bounds
    *derived* results; a pin is the caller's declared working set).
    ``unregister`` is the invalidation path; re-registering different
    bytes under a forced digest is refused."""

    def __init__(self, name: str = ""):
        self.name = str(name)
        self._lock = _locks.make_lock("cache.residency")
        self._pins: Dict[str, np.ndarray] = {}
        # request digest -> pinned result (a registered operand's
        # precomputed sketch), plus operand digest -> the request
        # digests it owns, so unregistering an operand drops its
        # pinned results with it
        self._results: Dict[str, np.ndarray] = {}
        self._owned: Dict[str, list] = {}

    def pin(self, digest: str, operand, replace: bool = False) -> str:
        value = freeze_result(np.asarray(operand))
        with self._lock:
            held = self._pins.get(digest)
            if held is not None and not replace:
                if (held.shape != value.shape
                        or held.dtype != value.dtype
                        or not np.array_equal(held, value)):
                    raise ValueError(
                        f"operand digest {digest[:12]}… is already "
                        f"pinned to different bytes")
                return digest
            self._pins[digest] = value
            n = len(self._pins)
        _RESIDENT.set(float(n), replica=self.name)
        return digest

    def pin_result(self, rdigest: str, value,
                   owner: Optional[str] = None) -> None:
        """Pin one precomputed result under its full *request* digest
        — the sketch-stage skip: a later submit whose digest matches
        resolves from here before the byte-bounded cache is even
        consulted, and a pin is never evicted. ``owner`` ties the
        result to a registered operand's digest so ``unpin(owner)``
        drops it too."""
        with self._lock:
            self._results[rdigest] = freeze_result(np.asarray(value))
            if owner is not None:
                self._owned.setdefault(owner, []).append(rdigest)

    def result(self, rdigest: str):
        with self._lock:
            return self._results.get(rdigest)

    def resolve(self, digest: str) -> np.ndarray:
        with self._lock:
            v = self._pins.get(digest)
        if v is None:
            raise KeyError(
                f"no resident operand for digest {digest[:12]}… on "
                f"{self.name or 'this executor'} — register_operand "
                f"it here (a fleet front door broadcasts pins to "
                f"every replica)")
        return v

    def unpin(self, digest: str) -> bool:
        with self._lock:
            found = self._pins.pop(digest, None) is not None
            for rd in self._owned.pop(digest, ()):
                self._results.pop(rd, None)
            n = len(self._pins)
        _RESIDENT.set(float(n), replica=self.name)
        return found

    def digests(self) -> list:
        with self._lock:
            return sorted(self._pins)

    def stats(self) -> dict:
        with self._lock:
            return {
                "resident_operands": len(self._pins),
                "pinned_results": len(self._results),
                "resident_bytes": int(sum(
                    v.nbytes for v in self._pins.values())),
            }


__all__ = [
    "OperandRef", "ResidencyTable", "ResultCache", "SingleFlight",
    "as_ref", "freeze_result", "is_ref", "MISS", "merge_cache_blocks",
    "operand_digest",
]
